"""graftmethyl tests: the fused methylation extraction subsystem.

* epilogue parity — the jit epilogue and its numpy host twin are the
  same integer formula; bit-identity is asserted on hand cases and
  randomized batches;
* mini-genome oracle — every emitted site's context/strand is re-derived
  by an independent string-walk over the genome (CpG/CHG/CHH on both
  strands, N suppression, contig ends);
* engine differential — wire (fused kernel tail), unpacked (device
  epilogue), BSSEQ_TPU_METHYL_ENGINE=host (numpy twin) and the degrade
  path all produce byte-identical bedMethyl/CX — and the consensus BAM
  is byte-identical to a methyl-free run;
* byte-goldens — SHA-pinned bedMethyl/CX from the deterministic fixture;
* spill/resume — the accumulator's watermark protocol replays cleanly
  (orphan runs dropped, idempotent re-adds, byte-identical finalize);
* chemistry — emseq == bisulfite bytes; 'none' runs the plain duplex
  engine transport-identically; forbidden combinations refuse loudly;
* serve — mixed-chemistry tenants share the engine, each job's output
  SHA equal to its standalone run, chemistry in the job status.
"""

import hashlib
import os
import types

import numpy as np
import pytest

from bsseqconsensusreads_tpu.io.bam import (
    BamHeader,
    BamWriter,
    write_items,
)
from bsseqconsensusreads_tpu.methyl import (
    CTX_NAMES,
    MethylAccumulator,
    merge_tallies,
    methyl_epilogue,
    methyl_epilogue_host,
)
from bsseqconsensusreads_tpu.ops.refstore import RefStore
from bsseqconsensusreads_tpu.pipeline.calling import (
    StageStats,
    call_duplex_batches,
)
from bsseqconsensusreads_tpu.utils.testing import (
    make_aligned_duplex_group,
    random_genome,
)

_A, _C, _G, _T, _N = 0, 1, 2, 3, 4


# ---------------------------------------------------------------------------
# fixture: the transport-test duplex shape + a methyl-aware runner


@pytest.fixture(scope="module")
def duplex_setup(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("methyl")
    rng = np.random.default_rng(11)
    _, g1 = random_genome(rng, 9000, name="chrA")
    _, g2 = random_genome(rng, 7000, name="chrB")
    genomes = {"chrA": g1, "chrB": g2}
    header = BamHeader(
        "@HD\tVN:1.6\tSO:coordinate\n", [("chrA", 9000), ("chrB", 7000)]
    )
    records = []
    for fam in range(40):
        ref_id = fam % 2
        gname = ("chrA", "chrB")[ref_id]
        start = 50 + (fam // 2) * 150
        if fam == 6:  # window runs past the contig end: context columns
            # there come back N and must be suppressed
            start = len(genomes[gname]) - 60
        recs = make_aligned_duplex_group(
            rng, gname, genomes[gname], fam, start, 60,
            softclip=3 if fam % 5 == 0 else 0,
        )
        for r in recs:
            r.ref_id = ref_id
            if fam == 9:
                r.ref_id = -1  # unmapped family: no reference, no sites
        records.extend(recs)
    records.sort(key=lambda r: (r.ref_id, r.pos))
    path = str(tmp / "dup_in.bam")
    with BamWriter(path, header) as w:
        w.write_all(records)
    # store contig order differs from the BAM header on purpose: global
    # site offsets must come from the name mapping, not raw ref_ids
    store = RefStore(["chrB", "chrA"], seqs=[g2, g1])
    return {
        "path": path, "header": header, "genomes": genomes, "store": store,
        "tmp": tmp,
    }


def _run(setup, transport, out_name, methyl_formats=("bed",), **kw):
    """One duplex stage run; returns {'bam': bytes, 'bed': bytes|None,
    'cx': bytes|None, 'report': dict|None}."""
    from bsseqconsensusreads_tpu.io.bam import BamReader

    genomes = setup["genomes"]

    def fetch(name, s, e):
        return genomes[name][s:e]

    kw.setdefault("mesh", None)
    kw.setdefault("refstore", setup["store"])
    kw.setdefault("stats", StageStats())
    acc = None
    bed = cx = None
    if methyl_formats:
        bed = (
            str(setup["tmp"] / (out_name + ".bedmethyl"))
            if "bed" in methyl_formats else None
        )
        cx = (
            str(setup["tmp"] / (out_name + ".CX_report.txt"))
            if "cx" in methyl_formats else None
        )
        acc = MethylAccumulator(setup["store"], bed, cx)
    with BamReader(setup["path"]) as reader:
        names = [n for n, _ in reader.header.references]
        batches = call_duplex_batches(
            reader, fetch, names, mode="self", grouping="coordinate",
            transport=transport, methyl=acc, **kw,
        )
        out = str(setup["tmp"] / out_name)
        with BamWriter(out, setup["header"], engine="python") as w:
            for b in batches:
                write_items(w, b)
    report = acc.finalize() if acc is not None else None
    return {
        "bam": open(out, "rb").read(),
        "bed": open(bed, "rb").read() if bed else None,
        "cx": open(cx, "rb").read() if cx else None,
        "report": report,
    }


# ---------------------------------------------------------------------------
# epilogue: hand case + randomized jnp/numpy bit-identity


def _hand_case():
    """One family, W=8, genome slice TACGCTAGGCAT (window = g[2:10])."""
    g = "TACGCTAGGCAT"
    code = {"A": _A, "C": _C, "G": _G, "T": _T}
    ref_ext = np.array([[code[c] for c in g]], dtype=np.int8)
    w = 8
    bases = np.full((1, 4, w), _N, np.int8)
    quals = np.full((1, 4, w), 30, np.int8)
    cover = np.zeros((1, 4, w), bool)
    # rows 99/163/83/147 -> convert rows are indices 1 and 2
    convert_mask = np.array([[False, True, True, False]])
    # col0 = ref C (CpG+): one untreated C (meth), one untreated T (unmeth)
    bases[0, 0, 0], cover[0, 0, 0] = _C, True
    bases[0, 3, 0], cover[0, 3, 0] = _T, True
    # col1 = ref G (CpG-): both treated rows read G (2 meth)
    bases[0, 1, 1], cover[0, 1, 1] = _G, True
    bases[0, 2, 1], cover[0, 2, 1] = _G, True
    # col2 = ref C (CHH+): an untreated C below the quality gate
    bases[0, 0, 2], cover[0, 0, 2] = _C, True
    quals[0, 0, 2] = 3
    cons_base = np.zeros((1, 2, w), np.int8)  # called everywhere
    return bases, quals, cover, convert_mask, cons_base, ref_ext


class TestEpilogue:
    def test_hand_case_contexts_and_counts(self):
        args = _hand_case()
        planes = methyl_epilogue_host(*args, min_q=20)
        ctx, counts = planes[0, 0], planes[0, 1]
        # TACGCTAGGCAT windows to CGCTAGGC: CpG+ CpG- CHH+ . . CHH- CHH- CHH+
        assert list(ctx) == [1, 4, 3, 0, 0, 6, 6, 3]
        assert counts[0] == (1 | (1 << 4))  # 1 meth, 1 unmeth
        assert counts[1] == 2              # 2 meth on the minus strand
        assert counts[2] == 0              # quality-gated observation
        assert counts[3] == 0 and counts[4] == 0

    def test_uncalled_columns_report_nothing(self):
        bases, quals, cover, cm, cons, ref_ext = _hand_case()
        cons = np.full_like(cons, _N)  # vote called no base anywhere
        planes = methyl_epilogue_host(
            bases, quals, cover, cm, cons, ref_ext, min_q=20
        )
        assert not planes.any()

    def test_n_reference_suppresses(self):
        bases, quals, cover, cm, cons, ref_ext = _hand_case()
        ref_ext = ref_ext.copy()
        ref_ext[0, 3] = _N  # CpG+ partner of col0 becomes N
        planes = methyl_epilogue_host(
            bases, quals, cover, cm, cons, ref_ext, min_q=20
        )
        assert planes[0, 0, 0] == 0 and planes[0, 1, 0] == 0
        # col1's reference base IS that N now -> no site at all
        assert planes[0, 0, 1] == 0

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_device_twin_bit_identity_randomized(self, seed):
        rng = np.random.default_rng(seed)
        f, w = 7, 24
        bases = rng.integers(0, 5, (f, 4, w)).astype(np.int8)
        quals = rng.integers(0, 45, (f, 4, w)).astype(np.int8)
        cover = rng.random((f, 4, w)) < 0.7
        cm = rng.random((f, 4)) < 0.5
        cons = rng.integers(0, 5, (f, 2, w)).astype(np.int8)
        ref_ext = rng.integers(0, 5, (f, w + 4)).astype(np.int8)
        dev = np.asarray(
            methyl_epilogue(bases, quals, cover, cm, cons, ref_ext, 20.0)
        )
        host = methyl_epilogue_host(
            bases, quals, cover, cm, cons, ref_ext, 20.0
        )
        assert dev.dtype == host.dtype == np.uint8
        assert np.array_equal(dev, host)


# ---------------------------------------------------------------------------
# mini-genome oracle: independent string-walk classification


def _oracle(genome: str, p: int):
    """(context name, strand) for genome position p, or None when the
    site is not callable — an independent re-derivation of the epilogue's
    classification for the oracle test."""
    n = len(genome)

    def at(i):
        return genome[i] if 0 <= i < n else "N"

    b = at(p)
    if b == "C":
        n1, n2 = at(p + 1), at(p + 2)
        if n1 == "G":
            return ("CpG", "+")
        if n1 == "N":
            return None
        if n2 == "G":
            return ("CHG", "+")
        if n2 == "N":
            return None
        return ("CHH", "+")
    if b == "G":
        m1, m2 = at(p - 1), at(p - 2)
        if m1 == "C":
            return ("CpG", "-")
        if m1 == "N":
            return None
        if m2 == "C":
            return ("CHG", "-")
        if m2 == "N":
            return None
        return ("CHH", "-")
    return None


_COMP = {"A": "T", "C": "G", "G": "C", "T": "A", "N": "N"}


def _oracle_tri(genome: str, p: int, minus: bool) -> str:
    n = len(genome)
    out = []
    for k in range(3):
        q = p - k if minus else p + k
        c = genome[q] if 0 <= q < n else "N"
        out.append(_COMP[c] if minus else c)
    return "".join(out)


class TestMiniGenomeOracle:
    def test_bedmethyl_contexts_match_oracle_exactly(self, duplex_setup):
        res = _run(duplex_setup, "unpacked", "oracle.bam")
        lines = res["bed"].decode().splitlines()
        assert len(lines) > 300
        seen_ctx = set()
        for ln in lines:
            cols = ln.split("\t")
            chrom, p0, name, strand, pct = (
                cols[0], cols[1], cols[3], cols[5], cols[10]
            )
            p = int(p0)
            got = _oracle(duplex_setup["genomes"][chrom], p)
            assert got == (name, strand), (ln, got)
            # the simulator methylates every CpG and converts everything
            # else: the percent column is fully determined by the context
            assert int(pct) == (100 if name == "CpG" else 0), ln
            seen_ctx.add((name, strand))
        # the fixture is large enough to exercise every context code
        assert seen_ctx == {
            (n, s) for n, s in CTX_NAMES.values()
        }

    def test_cx_report_matches_oracle(self, duplex_setup):
        res = _run(
            duplex_setup, "unpacked", "oracle_cx.bam", methyl_formats=("cx",)
        )
        lines = res["cx"].decode().splitlines()
        assert len(lines) > 300
        for ln in lines:
            chrom, pos1, strand, m, u, name, tri = ln.split("\t")
            p = int(pos1) - 1
            genome = duplex_setup["genomes"][chrom]
            assert _oracle(genome, p) == (name, strand), ln
            assert _oracle_tri(genome, p, strand == "-") == tri, ln
            assert int(m) + int(u) >= 1  # covered sites only


# ---------------------------------------------------------------------------
# engine differential: fused kernel == device epilogue == host twin ==
# degrade path, and consensus bytes never move


class TestEngineDifferential:
    def test_wire_unpacked_host_byte_identical(
        self, duplex_setup, monkeypatch
    ):
        wire = _run(duplex_setup, "wire", "dw.bam", methyl_formats=("bed", "cx"))
        plain = _run(
            duplex_setup, "unpacked", "du.bam", methyl_formats=("bed", "cx")
        )
        monkeypatch.setenv("BSSEQ_TPU_METHYL_ENGINE", "host")
        host = _run(
            duplex_setup, "wire", "dh.bam", methyl_formats=("bed", "cx")
        )
        assert wire["bed"] == plain["bed"] == host["bed"]
        assert wire["cx"] == plain["cx"] == host["cx"]
        assert wire["bam"] == plain["bam"] == host["bam"]
        assert wire["report"]["sites"] > 0

    def test_consensus_bytes_unchanged_by_methyl(self, duplex_setup):
        with_methyl = _run(duplex_setup, "wire", "m1.bam")
        without = _run(duplex_setup, "wire", "m0.bam", methyl_formats=())
        assert with_methyl["bam"] == without["bam"]

    def test_degrade_path_byte_identical(self, duplex_setup):
        from bsseqconsensusreads_tpu.faults import failpoints as _failpoints

        ref = _run(duplex_setup, "unpacked", "dg_ref.bam")
        _failpoints.arm("dispatch_kernel=raise:RuntimeError@stage=duplex")
        try:
            stats = StageStats()
            degraded = _run(duplex_setup, "unpacked", "dg.bam", stats=stats)
            assert stats.batches_degraded > 0
        finally:
            _failpoints.disarm()
        assert degraded["bed"] == ref["bed"]
        assert degraded["bam"] == ref["bam"]

    def test_packed_and_padded_layouts_identical(self, duplex_setup):
        packed = _run(duplex_setup, "unpacked", "lp.bam", layout="packed")
        padded = _run(duplex_setup, "unpacked", "lq.bam", layout="padded")
        assert packed["bed"] == padded["bed"]
        assert packed["bam"] == padded["bam"]

    def test_merge_engines_agree_end_to_end(self, duplex_setup, monkeypatch):
        from bsseqconsensusreads_tpu.io import wirepack

        if not wirepack.available():
            pytest.skip("wirepack library not built")
        monkeypatch.setenv("BSSEQ_TPU_METHYL_MERGE", "python")
        py = _run(duplex_setup, "unpacked", "mp.bam")
        monkeypatch.setenv("BSSEQ_TPU_METHYL_MERGE", "native")
        nat = _run(duplex_setup, "unpacked", "mn.bam")
        assert py["bed"] == nat["bed"]


# ---------------------------------------------------------------------------
# byte-goldens: the fixture is fully deterministic


class TestGoldens:
    def test_bedmethyl_and_cx_sha_pinned(self, duplex_setup):
        res = _run(
            duplex_setup, "unpacked", "golden.bam",
            methyl_formats=("bed", "cx"),
        )
        assert hashlib.sha256(res["bed"]).hexdigest() == (
            "193939c45c7c8d77025524b1a12baf081bb0fbecc351ce5648fe7e8bcd6ec247"
        )
        assert hashlib.sha256(res["cx"]).hexdigest() == (
            "d634997c82a7147d990bf8ae30a59b13dcf95ecc28ff219dc980bfb5912769c5"
        )


# ---------------------------------------------------------------------------
# chemistry modes


class TestChemistry:
    def test_emseq_identical_to_bisulfite(self, duplex_setup):
        bs = _run(duplex_setup, "unpacked", "cb.bam", chemistry="bisulfite")
        em = _run(duplex_setup, "unpacked", "ce.bam", chemistry="emseq")
        assert em["bam"] == bs["bam"] and em["bed"] == bs["bed"]

    def test_none_runs_plain_duplex_transport_identical(self, duplex_setup):
        """chemistry='none' (fgbio-style unconverted duplex) through the
        identical engine: wire, unpacked and the degrade path agree."""
        from bsseqconsensusreads_tpu.faults import failpoints as _failpoints

        plain = _run(
            duplex_setup, "unpacked", "n0.bam", methyl_formats=(),
            chemistry="none",
        )
        wire = _run(
            duplex_setup, "wire", "n1.bam", methyl_formats=(),
            chemistry="none",
        )
        _failpoints.arm("dispatch_kernel=raise:RuntimeError@stage=duplex")
        try:
            degraded = _run(
                duplex_setup, "unpacked", "n2.bam", methyl_formats=(),
                chemistry="none",
            )
        finally:
            _failpoints.disarm()
        assert wire["bam"] == plain["bam"] == degraded["bam"]
        assert len(plain["bam"]) > 200

    def test_none_differs_from_bisulfite(self, duplex_setup):
        """Disabling the conversion transform must actually change the
        engine's reading of converted evidence — 'none' is not a no-op
        spelling of 'bisulfite' on this fixture."""
        bs = _run(duplex_setup, "unpacked", "d0.bam", methyl_formats=())
        off = _run(
            duplex_setup, "unpacked", "d1.bam", methyl_formats=(),
            chemistry="none",
        )
        assert off["bam"] != bs["bam"]


# ---------------------------------------------------------------------------
# forbidden combinations refuse loudly


class TestForbiddenCombos:
    def test_unknown_chemistry(self, duplex_setup):
        with pytest.raises(ValueError, match="chemistry"):
            _run(duplex_setup, "unpacked", "x0.bam", chemistry="sanger")

    def test_methyl_needs_converting_chemistry(self, duplex_setup):
        with pytest.raises(ValueError, match="chemistry"):
            _run(duplex_setup, "unpacked", "x1.bam", chemistry="none")

    def test_none_refuses_passthrough(self, duplex_setup):
        with pytest.raises(ValueError, match="passthrough"):
            _run(
                duplex_setup, "unpacked", "x2.bam", methyl_formats=(),
                chemistry="none", passthrough=True,
            )

    def test_none_refuses_pos0_shift(self, duplex_setup):
        with pytest.raises(ValueError, match="pos0"):
            _run(
                duplex_setup, "unpacked", "x3.bam", methyl_formats=(),
                chemistry="none", pos0="shift",
            )

    def test_builder_validation(self, tmp_path):
        from bsseqconsensusreads_tpu.config import FrameworkConfig
        from bsseqconsensusreads_tpu.pipeline.stages import PipelineBuilder
        from bsseqconsensusreads_tpu.pipeline.workflow import WorkflowError

        bam = str(tmp_path / "absent.bam")

        def build(**kw):
            cfg = FrameworkConfig(aligner="self", group_umis="never", **kw)
            return PipelineBuilder(cfg, bam, outdir=str(tmp_path)).build()

        with pytest.raises(WorkflowError, match="chemistry"):
            build(chemistry="sanger")
        with pytest.raises(WorkflowError, match="methyl"):
            build(methyl="wig")
        with pytest.raises(WorkflowError, match="chemistry"):
            build(methyl="bedmethyl", chemistry="none")
        with pytest.raises(WorkflowError, match="single"):
            build(methyl="bedmethyl", single_strand=True)

    def test_accumulator_needs_an_output(self, duplex_setup):
        with pytest.raises(ValueError, match="bed_path or cx_path"):
            MethylAccumulator(duplex_setup["store"])


# ---------------------------------------------------------------------------
# accumulator: spill / watermark / resume protocol (in-process)


def _mk_tallies(rng, n, span=500):
    sites = np.sort(rng.integers(0, span, n)).astype(np.int64)
    ctx = (sites % 6 + 1).astype(np.uint8)  # pure function of the site
    meth = rng.integers(0, 3, n).astype(np.uint32)
    unmeth = rng.integers(0, 3, n).astype(np.uint32)
    return sites, ctx, meth + 1, unmeth  # cov >= 1 everywhere


class _FakeCk:
    def __init__(self, batches_done=0):
        self.batches_done = batches_done
        self.on_flush = None


class TestAccumulatorProtocol:
    @pytest.fixture()
    def store(self):
        rng = np.random.default_rng(5)
        return RefStore(
            ["c1"], seqs=["".join("ACGT"[i] for i in rng.integers(0, 4, 600))]
        )

    def _finalized_bytes(self, store, path, adds):
        acc = MethylAccumulator(store, str(path))
        for bi, t in adds:
            acc.add(bi, *t)
        acc.finalize()
        return open(path, "rb").read()

    def test_spill_resume_byte_identical(self, store, tmp_path):
        rng = np.random.default_rng(9)
        batches = {bi: _mk_tallies(rng, 40) for bi in (1, 2, 3, 4)}
        ref = self._finalized_bytes(
            store, tmp_path / "ref.bed", sorted(batches.items())
        )
        # checkpointed run: spill batches 1-2 at the committed watermark,
        # then "crash" with 3 pending and 4 never delivered
        bed = str(tmp_path / "r.bed")
        acc = MethylAccumulator(store, bed)
        acc.attach_checkpoint(_FakeCk())
        acc.add(1, *batches[1])
        acc.add(2, *batches[2])
        acc.flush(2)
        acc.add(3, *batches[3])
        del acc
        # resume at batches_done=2: the run survives, 3 and 4 replay
        acc2 = MethylAccumulator(store, bed)
        acc2.attach_checkpoint(_FakeCk(batches_done=2))
        acc2.add(3, *batches[3])
        acc2.add(4, *batches[4])
        acc2.finalize()
        assert open(bed, "rb").read() == ref

    def test_orphan_run_above_watermark_dropped(self, store, tmp_path):
        rng = np.random.default_rng(10)
        batches = {bi: _mk_tallies(rng, 30) for bi in (1, 2, 3, 4)}
        ref = self._finalized_bytes(
            store, tmp_path / "ref.bed", sorted(batches.items())
        )
        bed = str(tmp_path / "o.bed")
        acc = MethylAccumulator(store, bed)
        acc.attach_checkpoint(_FakeCk())
        for bi in (1, 2, 3, 4):
            acc.add(bi, *batches[bi])
        acc.flush(2)
        acc.flush(4)  # this run's manifest entry outruns the "commit"
        del acc
        # the checkpoint only committed through batch 2: run 2 is an
        # orphan and must be dropped, its batches replayed
        acc2 = MethylAccumulator(store, bed)
        acc2.attach_checkpoint(_FakeCk(batches_done=2))
        run1 = bed + ".methyl.run.0001"
        assert not os.path.exists(run1)
        acc2.add(3, *batches[3])
        acc2.add(4, *batches[4])
        acc2.finalize()
        assert open(bed, "rb").read() == ref

    def test_add_is_idempotent(self, store, tmp_path):
        rng = np.random.default_rng(11)
        batches = {bi: _mk_tallies(rng, 25) for bi in (1, 2)}
        ref = self._finalized_bytes(
            store, tmp_path / "ref.bed", sorted(batches.items())
        )
        bed = str(tmp_path / "i.bed")
        acc = MethylAccumulator(store, bed)
        acc.attach_checkpoint(_FakeCk())
        acc.add(1, *batches[1])
        acc.add(1, *batches[1])  # redispatch replay: replaces, no double
        acc.flush(1)
        acc.add(1, *batches[1])  # at the watermark: ignored
        acc.add(2, *batches[2])
        acc.finalize()
        assert open(bed, "rb").read() == ref

    def test_uncheckpointed_threshold_spill(self, store, tmp_path):
        rng = np.random.default_rng(12)
        batches = {bi: _mk_tallies(rng, 50) for bi in (1, 2, 3)}
        ref = self._finalized_bytes(
            store, tmp_path / "ref.bed", sorted(batches.items())
        )
        bed = str(tmp_path / "t.bed")
        acc = MethylAccumulator(store, bed, spill_sites=60)
        for bi in (1, 2, 3):
            acc.add(bi, *batches[bi])
        report = acc.finalize()
        assert open(bed, "rb").read() == ref
        assert report["sites"] > 0
        # finalize cleaned up its spill machinery
        assert not os.path.exists(bed + ".methyl.runs.json")


class TestMergeTallies:
    def test_python_merge_sums_duplicates(self):
        sites = np.array([5, 3, 5, 3, 9], np.int64)
        ctx = np.array([2, 1, 2, 1, 4], np.uint8)
        meth = np.array([1, 2, 3, 4, 5], np.uint32)
        unmeth = np.array([0, 1, 0, 1, 0], np.uint32)
        s, c, m, u = merge_tallies(sites, ctx, meth, unmeth, engine="python")
        assert list(s) == [3, 5, 9]
        assert list(c) == [1, 2, 4]
        assert list(m) == [6, 4, 5]
        assert list(u) == [2, 0, 0]

    def test_native_matches_python(self):
        from bsseqconsensusreads_tpu.io import wirepack

        if not wirepack.available():
            pytest.skip("wirepack library not built")
        rng = np.random.default_rng(3)
        sites = rng.integers(0, 200, 5000).astype(np.int64)
        ctx = (sites % 6 + 1).astype(np.uint8)
        meth = rng.integers(0, 10, 5000).astype(np.uint32)
        unmeth = rng.integers(0, 10, 5000).astype(np.uint32)
        py = merge_tallies(sites, ctx, meth, unmeth, engine="python")
        nat = merge_tallies(sites, ctx, meth, unmeth, engine="native")
        for a, b in zip(py, nat):
            assert np.array_equal(a, b)

    def test_extract_tallies_global_offsets(self):
        from bsseqconsensusreads_tpu.methyl import extract_tallies

        # store order is the REVERSE of the BAM header order: a raw
        # ref_id would land c1 sites inside c2's global range
        store = RefStore(["c2", "c1"], seqs=["ACGT" * 25, "ACGT" * 25])
        rid_map = store.contig_indices(["c1", "c2"])
        planes = np.zeros((2, 2, 6), np.uint8)
        planes[0, 0, 2], planes[0, 1, 2] = 1, 1 | (2 << 4)
        planes[1, 0, 4], planes[1, 1, 4] = 4, 3
        metas = [
            types.SimpleNamespace(ref_id=0, window_start=10),  # c1
            types.SimpleNamespace(ref_id=-1, window_start=10),  # unmapped
        ]
        sites, ctx, meth, unmeth = extract_tallies(
            planes, metas, store, rid_map
        )
        assert list(sites) == [100 + 10 + 2]  # c1 starts at offsets[1]
        assert list(ctx) == [1]
        assert list(meth) == [1] and list(unmeth) == [2]


# ---------------------------------------------------------------------------
# serve: mixed-chemistry tenants


class TestServeMixedChemistry:
    @pytest.fixture()
    def engine(self):
        from bsseqconsensusreads_tpu.serve import ServeEngine

        engines = []

        def make(**kw):
            kw.setdefault("batch_families", 4)
            kw.setdefault("stride", 2)
            eng = ServeEngine(**kw)
            engines.append(eng)
            eng.start()
            return eng

        yield make
        for eng in engines:
            eng.stop(timeout=30)

    @staticmethod
    def _grouped_bam(path, seed):
        from bsseqconsensusreads_tpu.utils.testing import (
            make_grouped_bam_records,
        )

        rng = np.random.default_rng(seed)
        genome = "".join(
            "ACGT"[i] for i in np.random.default_rng(7).integers(0, 4, 2000)
        )
        header, records = make_grouped_bam_records(
            rng, f"chr{seed % 97}", genome, n_families=5, read_len=40
        )
        with BamWriter(path, header) as w:
            for r in records:
                w.write(r)

    def test_mixed_chemistry_tenants_isolated(self, tmp_path, engine):
        from bsseqconsensusreads_tpu import cli

        chems = ["bisulfite", "none", "emseq"]
        inputs, refs = [], []
        for k in range(3):
            inp = str(tmp_path / f"in{k}.bam")
            self._grouped_bam(inp, seed=40 + k)
            inputs.append(inp)
            ref = str(tmp_path / f"ref{k}.bam")
            assert cli.main(
                ["molecular", "-i", inp, "-o", ref,
                 "--batching", "sequential"]
            ) == 0
            refs.append(hashlib.sha256(open(ref, "rb").read()).hexdigest())
        eng = engine()
        jobs = []
        for k, (inp, chem) in enumerate(zip(inputs, chems)):
            jobs.append(eng.submit({
                "input": inp, "output": str(tmp_path / f"out{k}.bam"),
                "chemistry": chem,
            }))
        for k, job in enumerate(jobs):
            st = eng.wait(job.id, timeout=120)
            assert st["state"] == "done"
            # chemistry is admission + provenance: it rides the status
            assert st["chemistry"] == chems[k]
            sha = hashlib.sha256(
                open(str(tmp_path / f"out{k}.bam"), "rb").read()
            ).hexdigest()
            # the molecular stage is chemistry-invariant: every tenant's
            # bytes equal its standalone run regardless of neighbors
            assert sha == refs[k]

    def test_unknown_chemistry_refused_at_admission(self, tmp_path, engine):
        from bsseqconsensusreads_tpu.serve import AdmissionError

        inp = str(tmp_path / "in.bam")
        self._grouped_bam(inp, seed=50)
        eng = engine()
        with pytest.raises(AdmissionError, match="chemistry"):
            eng.submit({
                "input": inp, "output": str(tmp_path / "o.bam"),
                "chemistry": "sanger",
            })


# ---------------------------------------------------------------------------
# single-strand consensus mode (molecular emit without duplex pairing)


class TestSingleStrand:
    def test_single_strand_stops_at_molecular(self, tmp_path):
        from bsseqconsensusreads_tpu.config import FrameworkConfig
        from bsseqconsensusreads_tpu.ops.encode import codes_to_seq
        from bsseqconsensusreads_tpu.pipeline.stages import run_pipeline
        from bsseqconsensusreads_tpu.utils.testing import (
            stream_duplex_families,
            write_fasta,
        )

        wd = str(tmp_path)
        rng = np.random.default_rng(21)
        codes = rng.integers(0, 4, size=6000).astype(np.int8)
        write_fasta(os.path.join(wd, "genome.fa"), "chr1",
                    codes_to_seq(codes))
        header = BamHeader(
            "@HD\tVN:1.6\tSO:coordinate\n", [("chr1", 6000)]
        )
        bam = os.path.join(wd, "in.bam")
        with BamWriter(bam, header) as w:
            for rec in stream_duplex_families(
                codes, 12, read_len=50, bisulfite=True
            ):
                w.write(rec)

        def run(sub, **kw):
            cfg = FrameworkConfig(
                genome_dir=wd, genome_fasta_file_name="genome.fa", tmp=wd,
                aligner="self", grouping="coordinate", batch_families=4,
                single_strand=True, **kw,
            )
            out = os.path.join(wd, sub)
            target, _, _ = run_pipeline(cfg, bam, outdir=out)
            return target, open(target, "rb").read()

        t1, b1 = run("o1")
        assert "molecular" in os.path.basename(t1)
        assert "duplex" not in os.path.basename(t1)
        # transport differential: the single-strand target is engine-
        # independent like every other stage output
        t2, b2 = run("o2", transport="unpacked")
        assert b1 == b2 and len(b1) > 200
