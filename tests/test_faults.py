"""faults/ subsystem: failpoint grammar + registry, the batch retry
executor with CPU-twin degrade, the stall watchdog, durable-state
integrity, the overlap-pool teardown contract, and the tier-1 chaos
smoke (a scheduled-fault mini pipeline whose output must be
byte-identical to a fault-free run, with non-zero recovery counters in
the ledger).
"""

from __future__ import annotations

import gc
import json
import os
import threading

import numpy as np
import pytest

from bsseqconsensusreads_tpu.faults import failpoints, integrity, retry
from bsseqconsensusreads_tpu.pipeline.calling import (
    StageStats,
    call_molecular,
    call_molecular_batches,
)
from bsseqconsensusreads_tpu.utils import observe
from bsseqconsensusreads_tpu.utils.testing import (
    make_grouped_bam_records,
    random_genome,
)


@pytest.fixture(autouse=True)
def _disarmed(monkeypatch):
    """Every test starts and ends unarmed, with fast retry backoff."""
    monkeypatch.setenv("BSSEQ_TPU_RETRY_BACKOFF_S", "0.001")
    failpoints.disarm()
    yield
    failpoints.disarm()


@pytest.fixture()
def ledger(tmp_path, monkeypatch):
    sink = str(tmp_path / "ledger.jsonl")
    monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
    yield sink
    observe.close_sinks()


def ledger_events(sink: str, event: str | None = None) -> list[dict]:
    if not os.path.exists(sink):
        return []
    out = []
    with open(sink) as fh:
        for line in fh:
            d = json.loads(line)
            if event is None or d.get("event") == event:
                out.append(d)
    return out


@pytest.fixture(scope="module")
def grouped():
    rng = np.random.default_rng(91)
    gname, genome = random_genome(rng, 3000)
    header, records = make_grouped_bam_records(
        rng, gname, genome, n_families=24
    )
    return header, records


def canon(recs) -> list:
    return [(x.qname, x.flag, x.seq, x.qual) for x in recs]


def run_single_device(records, stats=None, **kw):
    """Molecular stage pinned to mesh=None: the conftest forces an
    8-device virtual mesh, whose sharded path disables the overlap pool
    — the pool/watchdog tests need the single-device path."""
    return canon(
        x
        for b in call_molecular_batches(
            iter(records), batch_families=4, mesh=None, stats=stats, **kw
        )
        for x in b
    )


# ---------------------------------------------------------------------------
# grammar + registry


class TestGrammar:
    def test_full_grammar(self):
        pts = failpoints.parse_schedule(
            "dispatch_kernel=raise:RuntimeError:times=1@batch=7;"
            "extsort_spill=io_error:p=0.01:seed=42,"
            "fetch_out=stall:30s@batch=3,"
            "ckpt_finalize=exit:9@hit=2@stage=duplex"
        )
        assert [(p.site, p.action) for p in pts] == [
            ("dispatch_kernel", "raise"),
            ("extsort_spill", "io_error"),
            ("fetch_out", "stall"),
            ("ckpt_finalize", "exit"),
        ]
        assert pts[0].times == 1 and pts[0].batch == 7
        assert pts[1].prob == 0.01 and pts[1].seed == 42
        assert pts[2].duration_s == 30.0
        assert pts[3].exit_code == 9 and pts[3].hit == 2
        assert pts[3].stage == "duplex"

    @pytest.mark.parametrize(
        "bad",
        [
            "no_such_site=raise",
            "dispatch_kernel=frobnicate",
            "dispatch_kernel",
            "dispatch_kernel=raise:NoSuchError",
            "dispatch_kernel=raise@planet=mars",
            "dispatch_kernel=raise:p=xyz",
        ],
    )
    def test_bad_schedules_error(self, bad):
        with pytest.raises(failpoints.FailpointError):
            failpoints.parse_schedule(bad)

    def test_every_site_is_registered(self):
        for site in failpoints.SITES:
            failpoints.parse_schedule(f"{site}=raise")

    def test_batch_predicate_and_times(self):
        failpoints.arm("dispatch_kernel=raise:times=1@batch=2")
        failpoints.fire("dispatch_kernel", batch=1)  # predicate mismatch
        with pytest.raises(RuntimeError):
            failpoints.fire("dispatch_kernel", batch=2)
        failpoints.fire("dispatch_kernel", batch=2)  # times exhausted
        assert failpoints.fired_counts() == {"dispatch_kernel": 1}

    def test_hit_predicate(self):
        failpoints.arm("ckpt_finalize=raise@hit=2")
        failpoints.fire("ckpt_finalize")
        with pytest.raises(RuntimeError):
            failpoints.fire("ckpt_finalize")
        failpoints.fire("ckpt_finalize")  # hit 3 != 2

    def test_probability_is_seed_deterministic(self):
        def fires(seed):
            failpoints.arm(f"bgzf_write=raise:p=0.5:seed={seed}")
            got = []
            for _ in range(32):
                try:
                    failpoints.fire("bgzf_write")
                    got.append(0)
                except RuntimeError:
                    got.append(1)
            return got

        a, b = fires(42), fires(42)
        assert a == b
        assert 0 < sum(a) < 32
        assert fires(43) != a

    def test_io_error_action_raises_oserror(self):
        failpoints.arm("extsort_spill=io_error")
        with pytest.raises(OSError):
            failpoints.fire("extsort_spill")

    def test_unarmed_is_silent_and_eventless(self, ledger):
        failpoints.fire("dispatch_kernel", batch=1)
        assert failpoints.fired_counts() == {}
        assert ledger_events(ledger) == []

    def test_fired_failpoint_is_ledgered(self, ledger):
        failpoints.arm("dispatch_kernel=raise:times=1")
        with pytest.raises(RuntimeError):
            failpoints.fire("dispatch_kernel", batch=4, stage="molecular")
        (ev,) = ledger_events(ledger, "failpoint_fired")
        assert ev["site"] == "dispatch_kernel"
        assert ev["batch"] == 4 and ev["stage"] == "molecular"


# ---------------------------------------------------------------------------
# retry executor


class TestRetryExecutor:
    def test_transient_failure_recovers(self, ledger):
        m = observe.Metrics()
        calls = []

        def unit():
            calls.append(1)
            if len(calls) < 3:
                raise RuntimeError("transient")
            return "ok"

        out = retry.guarded(unit, metrics=m, stage="s", batch=7,
                            sleep=lambda s: None)
        assert out == "ok" and len(calls) == 3
        assert m.counters["batches_retried"] == 1
        assert m.counters["retry_attempts"] == 2
        assert m.counters["batches_recovered"] == 1
        assert len(ledger_events(ledger, "batch_retry")) == 2
        assert len(ledger_events(ledger, "batch_recovered")) == 1

    def test_persistent_failure_degrades(self, ledger):
        m = observe.Metrics()

        def unit():
            raise RuntimeError("persistent")

        out = retry.guarded(unit, degrade=lambda: "twin", metrics=m,
                            sleep=lambda s: None)
        assert out == "twin"
        assert m.counters["batches_degraded"] == 1
        assert len(ledger_events(ledger, "batch_degraded")) == 1

    def test_no_degrade_reraises_after_bound(self):
        calls = []

        def unit():
            calls.append(1)
            raise OSError("disk")

        with pytest.raises(OSError):
            retry.guarded(unit, sleep=lambda s: None)
        assert len(calls) == retry.policy_from_env().max_attempts

    def test_programming_errors_are_not_retried(self):
        calls = []

        def unit():
            calls.append(1)
            raise ValueError("bug")

        with pytest.raises(ValueError):
            retry.guarded(unit, degrade=lambda: "no", sleep=lambda s: None)
        assert len(calls) == 1

    def test_failed_seed_counts_as_first_attempt(self):
        calls = []
        retry.guarded(
            lambda: calls.append(1), failed=RuntimeError("pre"),
            sleep=lambda s: None,
        )
        assert len(calls) == 1

    def test_backoff_grows_and_caps(self):
        slept = []
        calls = []

        def unit():
            calls.append(1)
            if len(calls) < 4:
                raise RuntimeError("x")

        retry.guarded(
            unit, sleep=slept.append,
            policy=retry.RetryPolicy(max_attempts=5, backoff_s=0.5,
                                     backoff_cap_s=1.0),
        )
        assert slept == [0.5, 1.0, 1.0]


# ---------------------------------------------------------------------------
# stage-level recovery (the batch loop heals itself)


class TestStageRecovery:
    def test_transient_dispatch_failure_output_identical(self, grouped):
        _, records = grouped
        want = canon(call_molecular(iter(records), batch_families=4))
        failpoints.arm("dispatch_kernel=raise:RuntimeError:times=1@batch=2")
        stats = StageStats()
        got = canon(
            call_molecular(iter(records), batch_families=4, stats=stats)
        )
        assert got == want
        assert stats.batches_retried == 1
        assert stats.batches_recovered == 1
        assert stats.batches_degraded == 0
        assert stats.as_dict()["batches_retried"] == 1

    def test_fetch_failure_redispatches_whole_unit(self, grouped):
        _, records = grouped
        want = canon(call_molecular(iter(records), batch_families=4))
        failpoints.arm("fetch_out=io_error:times=1@batch=3")
        stats = StageStats()
        got = canon(
            call_molecular(iter(records), batch_families=4, stats=stats)
        )
        assert got == want and stats.batches_retried == 1

    def test_persistent_failure_degrades_to_host_twin(self, grouped, ledger):
        _, records = grouped
        want = canon(call_molecular(iter(records), batch_families=4))
        failpoints.arm("dispatch_kernel=raise:RuntimeError@batch=2")
        stats = StageStats()
        got = canon(
            call_molecular(iter(records), batch_families=4, stats=stats)
        )
        assert got == want
        assert stats.batches_degraded == 1
        assert ledger_events(ledger, "batch_degraded")
        assert stats.metrics.seconds.get("degrade", 0) > 0

    def test_stall_watchdog_redispatches(self, grouped, monkeypatch, ledger):
        monkeypatch.setenv("BSSEQ_TPU_OVERLAP_THREADS", "1")
        monkeypatch.setenv("BSSEQ_TPU_STALL_TIMEOUT_S", "0.2")
        _, records = grouped
        failpoints.arm("fetch_out=stall:1.5s:times=1@batch=1")
        stats = StageStats()
        got = run_single_device(records, stats)
        failpoints.disarm()
        monkeypatch.delenv("BSSEQ_TPU_OVERLAP_THREADS")
        monkeypatch.delenv("BSSEQ_TPU_STALL_TIMEOUT_S")
        want = run_single_device(records)
        assert got == want
        assert stats.batches_stalled >= 1
        assert ledger_events(ledger, "batch_stall_redispatch")

    def test_retire_future_failure_recovers(self, grouped, monkeypatch):
        monkeypatch.setenv("BSSEQ_TPU_OVERLAP_THREADS", "1")
        _, records = grouped
        failpoints.arm("retire_future=raise:RuntimeError:times=1")
        stats = StageStats()
        got = run_single_device(records, stats)
        failpoints.disarm()
        monkeypatch.delenv("BSSEQ_TPU_OVERLAP_THREADS")
        want = run_single_device(records)
        assert got == want and stats.batches_retried == 1


# ---------------------------------------------------------------------------
# overlap-pool / round-robin teardown (ISSUE 3 satellite): a batch that
# raises mid-flight must not leak its device allocation or wedge the pool


class TestTeardown:
    def test_wire_roundrobin_dispatch_failure_no_leak(self, grouped):
        """Injected dispatch failure on the multi-device round-robin wire
        path: the batch retires exactly once (byte-identical stream) and
        the failed attempt's device wire buffer does not outlive the
        stage. The round-robin advance consumed by the failed attempt is
        benign — the ring is cyclic, the retry just lands on the next
        device."""
        import jax

        _, records = grouped

        def run(stats=None):
            return canon(
                x
                for b in call_molecular_batches(
                    iter(records), batch_families=4, transport="wire",
                    mesh="auto", stats=stats,
                )
                for x in b
            )

        want = run()  # warm jit/device caches
        gc.collect()
        baseline = len(jax.live_arrays())
        failpoints.arm("dispatch_kernel=raise:RuntimeError:times=1@batch=2")
        stats = StageStats()
        got = run(stats)
        assert got == want and stats.batches_retried == 1
        gc.collect()
        # the failed dispatch's wire buffer must not survive the stage
        assert len(jax.live_arrays()) <= baseline

    def test_abandoned_stream_shuts_pool_down(self, grouped, monkeypatch):
        monkeypatch.setenv("BSSEQ_TPU_OVERLAP_THREADS", "2")
        _, records = grouped
        gen = call_molecular_batches(
            iter(records), batch_families=4, mesh=None
        )
        next(gen)
        gen.close()  # consumer abandons mid-stream
        alive = [
            t.name for t in threading.enumerate()
            if t.name.startswith("bsseq-ovl") and t.is_alive()
        ]
        assert alive == []


# ---------------------------------------------------------------------------
# io / native / multihost sites


class TestIoSites:
    def test_bgzf_inflate_fault_surfaces_as_io_error(self, tmp_path):
        from bsseqconsensusreads_tpu.io.bgzf import BgzfReader, BgzfWriter

        path = str(tmp_path / "x.bgzf")
        with BgzfWriter.open(path) as w:
            w.write(b"x" * 100)
        failpoints.arm("bgzf_inflate=io_error:times=1")
        with pytest.raises(OSError):
            with BgzfReader.open(path) as r:
                r.read_all()
        # second read: the schedule is exhausted, decode is intact
        with BgzfReader.open(path) as r:
            assert r.read_all() == b"x" * 100

    def test_bgzf_write_fault(self, tmp_path):
        from bsseqconsensusreads_tpu.io.bgzf import BgzfWriter

        failpoints.arm("bgzf_write=io_error")
        with pytest.raises(OSError):
            w = BgzfWriter.open(str(tmp_path / "y.bgzf"))
            w.write(b"y" * 100)
            w.flush()

    def test_native_load_fault_degrades_to_python(self):
        from bsseqconsensusreads_tpu.io._nativelib import load_library

        failpoints.arm("native_load=raise:RuntimeError")
        lib, err = load_library("libbamio.so", "bamio.cpp")
        assert lib is None and "failpoint injected" in err

    def test_heartbeat_loss_drops_beat_but_leaves_evidence(self, ledger):
        from bsseqconsensusreads_tpu.parallel.multihost import WorkerHeartbeat

        hb = WorkerHeartbeat("t")
        hb.beat()
        assert len(ledger_events(ledger, "worker_heartbeat")) == 1
        failpoints.arm("multihost_heartbeat=raise:times=1")
        hb.beat()  # lost: no heartbeat event, but the firing is ledgered
        assert len(ledger_events(ledger, "worker_heartbeat")) == 1
        assert len(ledger_events(ledger, "failpoint_fired")) == 1
        hb.beat()
        assert len(ledger_events(ledger, "worker_heartbeat")) == 2

    def test_collective_fault_propagates(self):
        from bsseqconsensusreads_tpu.parallel.multihost import (
            global_family_batch,
            multihost_family_mesh,
        )

        mesh = multihost_family_mesh()
        n = mesh.devices.size
        arr = np.zeros((n, 4), np.int8)
        failpoints.arm("multihost_collective=raise:RuntimeError:times=1")
        with pytest.raises(RuntimeError):
            global_family_batch((arr,), n, mesh)
        (out,) = global_family_batch((arr,), n, mesh)  # healthy after
        assert out.shape == (n, 4)


# ---------------------------------------------------------------------------
# integrity


class TestIntegrity:
    def test_crc_roundtrip_and_mismatch(self, tmp_path, ledger):
        p = tmp_path / "blob.bin"
        p.write_bytes(b"abc" * 1000)
        crc = integrity.file_crc32(str(p))
        integrity.verify_file_crc32(str(p), crc)
        p.write_bytes(b"abd" * 1000)
        with pytest.raises(integrity.IntegrityError):
            integrity.verify_file_crc32(str(p), crc)
        assert ledger_events(ledger, "integrity_mismatch")

    def test_missing_file_is_integrity_error(self, tmp_path):
        with pytest.raises(integrity.IntegrityError):
            integrity.verify_file_crc32(str(tmp_path / "gone"), 0)

    def test_spill_run_corruption_fails_merge(self, grouped, tmp_path):
        """A spill run corrupted on disk between spill and merge is an
        IntegrityError at merge open — never silently merged. The
        corruption happens mid-iteration (after the first run spilled,
        before the merge opens it), like a bad disk would do it."""
        import glob

        from bsseqconsensusreads_tpu.pipeline.extsort import external_sort
        from bsseqconsensusreads_tpu.pipeline.record_ops import coordinate_key

        header, records = grouped

        def corrupting(recs):
            for i, rec in enumerate(recs):
                if i == 25:  # first run (buffer 10) is on disk by now
                    (run0,) = glob.glob(
                        str(tmp_path / "bsseq_extsort_*" / "run00000.bam")
                    )
                    blob = bytearray(open(run0, "rb").read())
                    blob[len(blob) // 2] ^= 0xFF
                    open(run0, "wb").write(bytes(blob))
                yield rec

        gen = external_sort(
            corrupting(iter(records)), coordinate_key, header,
            workdir=str(tmp_path), buffer_records=10,
        )
        with pytest.raises(integrity.IntegrityError):
            list(gen)

    def test_spill_io_error_retried(self, grouped):
        from bsseqconsensusreads_tpu.pipeline.extsort import external_sort
        from bsseqconsensusreads_tpu.pipeline.record_ops import coordinate_key

        header, records = grouped
        want = [
            r.qname
            for r in external_sort(
                iter(records), coordinate_key, header, buffer_records=10
            )
        ]
        failpoints.arm("extsort_spill=io_error:times=1")
        m = observe.Metrics()
        got = [
            r.qname
            for r in external_sort(
                iter(records), coordinate_key, header, buffer_records=10,
            )
        ]
        assert got == want


# ---------------------------------------------------------------------------
# tier-1 chaos smoke: scheduled faults over the mini pipeline, output
# byte-identical, recovery counters non-zero in the ledger


class TestChaosSmoke:
    def _run(self, tmp_path, outdir, monkeypatch, sink):
        from bsseqconsensusreads_tpu.config import FrameworkConfig
        from bsseqconsensusreads_tpu.pipeline.stages import run_pipeline

        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        cfg = FrameworkConfig(
            genome_dir=str(tmp_path), genome_fasta_file_name="genome.fa",
            tmp=str(tmp_path), aligner="self", backend="cpu",
            grouping="coordinate", batch_families=8, checkpoint_every=2,
            sort_buffer_records=32,
        )
        target, _, stats = run_pipeline(
            cfg, str(tmp_path / "input" / "in.bam"), outdir=outdir
        )
        observe.flush_sinks()
        observe.close_sinks()
        return target, stats

    def test_scheduled_faults_byte_identical(self, tmp_path, monkeypatch):
        from bsseqconsensusreads_tpu.io.bam import BamHeader, BamWriter
        from bsseqconsensusreads_tpu.ops.encode import codes_to_seq
        from bsseqconsensusreads_tpu.utils.testing import (
            stream_duplex_families,
            write_fasta,
        )

        rng = np.random.default_rng(88)
        codes = rng.integers(0, 4, size=12_000).astype(np.int8)
        write_fasta(str(tmp_path / "genome.fa"), "chr1", codes_to_seq(codes))
        header = BamHeader(
            "@HD\tVN:1.6\tSO:coordinate\n", [("chr1", 12_000)]
        )
        os.makedirs(tmp_path / "input")
        with BamWriter(str(tmp_path / "input" / "in.bam"), header) as w:
            for rec in stream_duplex_families(
                codes, 40, read_len=60, bisulfite=True,
                templates_for=lambda f: 1 if f % 3 else 2,
            ):
                w.write(rec)

        plain_sink = str(tmp_path / "plain.jsonl")
        target, _ = self._run(
            tmp_path, str(tmp_path / "out_plain"), monkeypatch, plain_sink
        )
        want = open(target, "rb").read()
        # an unarmed run emits no fault/recovery events at all
        assert ledger_events(plain_sink, "failpoint_fired") == []
        assert ledger_events(plain_sink, "batch_retry") == []

        failpoints.arm(
            "dispatch_kernel=raise:RuntimeError:times=1@stage=molecular;"
            "fetch_out=io_error:times=1@stage=duplex;"
            "extsort_spill=io_error:times=1"
        )
        sink = str(tmp_path / "chaos.jsonl")
        target2, stats = self._run(
            tmp_path, str(tmp_path / "out_chaos"), monkeypatch, sink
        )
        failpoints.disarm()
        assert open(target2, "rb").read() == want
        assert len(ledger_events(sink, "failpoint_fired")) == 3
        assert stats["molecular"].batches_retried >= 1
        assert stats["duplex"].batches_retried >= 1
        # the stage_stats ledger lines carry the recovery counters
        mol = [
            e for e in ledger_events(sink, "stage_stats")
            if e["stage"] == "molecular"
        ]
        assert mol and mol[0]["batches_retried"] >= 1
