"""A SECOND, independent transcription of fgbio's published consensus
model — the round-3 verdict's fidelity demand (VERDICT item 3): the
kernel was only ever validated against utils/oracle.py, written by the
same author from the same reading; a shared misreading would pass. This
module re-derives the same documented semantics by a DIFFERENT route so
a misreading would have to happen twice, differently, to agree:

* probability domain, base-10, float64 PRODUCTS of per-observation
  likelihoods (the oracle and the kernels work in log-likelihood SUMS);
* the documented two-process error combination written in its
  published closed form  p1 + p2 - (4/3) p1 p2  (error in either
  process, minus both-err-and-restore under uniform substitution; the
  oracle composes it as p1(1-p2) + (1-p1)p2 + (2/3)p1p2);
* scalar Python throughout, no imports from bsseqconsensusreads_tpu
  beyond nothing at all — base codes are plain ints 0..3, 4 = N.

Documented semantics transcribed (fgbio CallMolecularConsensusReads /
CallDuplexConsensusReads tool docs; flag surface = the reference's
main.snake.py:54,163):

1. each raw base quality is adjusted by the post-UMI error rate (the
   two-process rule above);
2. per column, for each candidate base: likelihood = product over
   observations of (1 - p_i) if the observation is the candidate else
   p_i / 3; observations that are N or below --min-input-base-quality
   are excluded;
3. consensus base = the likelihood argmax; its error probability is
   1 - L(cons) / sum(L); that error is combined with the pre-UMI error
   rate by the same two-process rule, converted to Phred, clamped to
   [2, 93], and rounded; below --min-consensus-base-quality the call
   masks to N / qual 2;
4. --consensus-call-overlapping-bases=true co-calls R1/R2 overlap
   first: agreement keeps the base at the summed quality, disagreement
   keeps the higher-quality base at the quality difference, an exact
   tie masks both;
5. the duplex call is the same vote over the two strand consensi.
"""

from __future__ import annotations

NBASE = 4
NO_CALL = 2


def _perr(q: float) -> float:
    return 10.0 ** (-q / 10.0)


def _two_process(p1: float, p2: float) -> float:
    # published closed form: error in either process, minus the chance
    # both err and the second lands back on the original base
    return p1 + p2 - (4.0 / 3.0) * p1 * p2


def _to_phred(p: float) -> float:
    import math

    p = min(max(p, 1e-12), 1.0)
    return min(max(-10.0 * math.log10(p), 2.0), 93.0)


def column_likelihoods(bases, quals, *, post_umi=30.0, min_input_q=0.0):
    """(per-candidate likelihood products, kept observations)."""
    p_post = _perr(post_umi)
    obs = []
    for b, q in zip(bases, quals):
        if b == NBASE or q < min_input_q:
            continue
        p = _two_process(_perr(float(q)), p_post)
        # the same numeric floor/ceiling the likelihood terms need to
        # stay finite (log route) / nonzero (product route)
        obs.append((b, min(max(p, 1e-12), 1.0 - 1e-7)))
    likes = []
    for cand in range(4):
        like = 1.0
        for b, p in obs:
            like *= (1.0 - p) if b == cand else (p / 3.0)
        likes.append(like)
    return likes, obs


def tied_candidates(bases, quals, *, post_umi=30.0, min_input_q=0.0,
                    rel=3e-6):
    """Candidates whose likelihood ties the max within `rel`.

    Two tie sources: an exact mathematical tie (same multiset of
    factors) breaks on summation-order ulps in the log-domain
    implementations; and a float32-resolution collapse — the kernels
    fold quals through the two-process rule in float32, where adjusted
    error probabilities that differ by less than ~1e-7 relative (e.g.
    raw quals 93 vs 95 under post-UMI 30) round together, compounding to
    ~1e-6 over a deep column's product. `rel` sits above that band and
    far below any semantic divergence (a wrong formula/clamp/prior moves
    likelihoods by orders of magnitude)."""
    likes, obs = column_likelihoods(
        bases, quals, post_umi=post_umi, min_input_q=min_input_q
    )
    if not obs:
        return {NBASE}
    m = max(likes)
    return {c for c in range(4) if likes[c] >= m * (1.0 - rel)}


def column_call(bases, quals, *, pre_umi=45.0, post_umi=30.0,
                min_input_q=0.0, min_consensus_q=0.0):
    """One column: observation base codes + Phred quals ->
    (base, qual, depth, errors)."""
    likes, obs = column_likelihoods(
        bases, quals, post_umi=post_umi, min_input_q=min_input_q
    )
    if not obs:
        return NBASE, NO_CALL, 0, 0
    best = max(range(4), key=lambda c: likes[c])
    total = sum(likes)
    p_cons = 1.0 - likes[best] / total
    qual = _to_phred(_two_process(p_cons, _perr(pre_umi)))
    if qual < min_consensus_q:
        return NBASE, NO_CALL, len(obs), 0
    errors = sum(1 for b, _ in obs if b != best)
    return best, int(round(qual)), len(obs), errors


def cocall_pair(b1, q1, b2, q2):
    """Overlap co-call of one R1/R2 column pair -> ((b1', q1'), (b2', q2'))."""
    if b1 == NBASE or b2 == NBASE:
        return (b1, q1), (b2, q2)
    if b1 == b2:
        return (b1, q1 + q2), (b2, q1 + q2)
    if q1 == q2:
        return (NBASE, 0), (NBASE, 0)
    win = b1 if q1 > q2 else b2
    d = abs(q1 - q2)
    return (win, d), (win, d)


def family_call(reads, *, overlap=True, **kw):
    """One single-strand family -> per-role consensus.

    reads: list of templates; each template is a pair (r1, r2) with
    r = (bases list, quals list) aligned to a common window (4 = no
    coverage). Returns {role: (bases, quals, depths, errors)}.
    """
    w = len(reads[0][0][0])
    cooked = []
    for (b1, q1), (b2, q2) in reads:
        nb1, nq1 = list(b1), list(q1)
        nb2, nq2 = list(b2), list(q2)
        if overlap:
            for i in range(w):
                (nb1[i], nq1[i]), (nb2[i], nq2[i]) = cocall_pair(
                    b1[i], q1[i], b2[i], q2[i]
                )
        cooked.append(((nb1, nq1), (nb2, nq2)))
    out = {}
    for role in range(2):
        bases, quals, depths, errors = [], [], [], []
        for i in range(w):
            col_b = [t[role][0][i] for t in cooked]
            col_q = [t[role][1][i] for t in cooked]
            b, q, d, e = column_call(col_b, col_q, **kw)
            bases.append(b)
            quals.append(q)
            depths.append(d)
            errors.append(e)
        out[role] = (bases, quals, depths, errors)
    return out


def duplex_call(a_strand, b_strand, **kw):
    """Duplex merge of two strand-consensus reads (per role window
    lists): the same column vote at depth <= 2."""
    bases, quals, depths, errors = [], [], [], []
    for i in range(len(a_strand[0])):
        b, q, d, e = column_call(
            [a_strand[0][i], b_strand[0][i]],
            [a_strand[1][i], b_strand[1][i]],
            **kw,
        )
        bases.append(b)
        quals.append(q)
        depths.append(d)
        errors.append(e)
    return bases, quals, depths, errors
