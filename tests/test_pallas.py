"""Pallas column-vote kernel vs the XLA reference kernel.

Runs the kernel in interpret mode on CPU (the test mesh never touches the
real TPU); the kernel body is the exact jnp expression set of ops/phred.py,
so results must be bitwise identical to models.molecular.column_vote /
molecular_consensus.
"""

import numpy as np
import pytest

from bsseqconsensusreads_tpu.alphabet import NBASE
from bsseqconsensusreads_tpu.models.molecular import (
    column_vote,
    molecular_consensus,
)
from bsseqconsensusreads_tpu.models.params import ConsensusParams
from bsseqconsensusreads_tpu.ops.pallas_vote import (
    column_vote_groups,
    molecular_consensus_pallas,
)


def _random_groups(rng, g, t, w, p_cover=0.8):
    bases = rng.integers(0, 5, size=(g, t, w)).astype(np.int8)
    cover = rng.random((g, t, w)) < p_cover
    bases[~cover] = NBASE
    quals = np.where(
        bases != NBASE, rng.integers(2, 41, size=(g, t, w)), 0
    ).astype(np.float32)
    return bases, quals


def _tie_columns(bases_g, quals_g, params):
    """Columns whose top-2 log-likelihoods tie (within float noise): the
    consensus pick there is genuinely ambiguous — equal posterior — and
    summation-order ulps may break the tie differently between the XLA and
    Pallas reductions. Those columns are excluded from exact comparison."""
    from bsseqconsensusreads_tpu.models.molecular import vote_partials

    ll = np.asarray(vote_partials(bases_g, quals_g, params)[0])  # [W, 4]
    top2 = np.sort(ll, axis=-1)[:, -2:]
    # fp32 summation-order error grows with the reads summed per column:
    # at depth 128 a genuine near-tie can sit several ulp-sums past a
    # fixed 1e-4, flipping between the XLA and Pallas reduction orders —
    # scale the ambiguity band with depth (still far below any
    # non-ambiguous log-likelihood gap)
    tol = 1e-4 * max(1.0, bases_g.shape[0] / 16)
    return np.abs(top2[:, 1] - top2[:, 0]) <= tol


def _assert_vote_matches(got_g, want, tie, tag=""):
    free = ~tie
    for k in ("base", "qual", "depth", "errors"):
        a, b = np.asarray(got_g[k]), np.asarray(want[k])
        np.testing.assert_array_equal(a[free], b[free], err_msg=f"{k}{tag}")
    # tie columns: depth is still exact, qual within rounding of the tie
    np.testing.assert_array_equal(
        np.asarray(got_g["depth"])[tie], np.asarray(want["depth"])[tie]
    )
    assert (
        np.abs(
            np.asarray(got_g["qual"])[tie].astype(int)
            - np.asarray(want["qual"])[tie].astype(int)
        )
        <= 1
    ).all()


@pytest.mark.parametrize(
    "g,t,w",
    [
        (3, 5, 40),
        (8, 128, 160),
        (9, 130, 33),
        (2, 1, 16),  # cfDNA tail: single-read family, tiny read chunk
        (3, 4, 600),  # wide window: exercises the column-tile grid axis
    ],
)
def test_vote_groups_match_xla(rng, g, t, w):
    bases, quals = _random_groups(rng, g, t, w)
    params = ConsensusParams()
    got = column_vote_groups(bases, quals, params, interpret=True)
    for gi in range(g):
        want = column_vote(bases[gi], quals[gi], params)
        tie = _tie_columns(bases[gi], quals[gi], params)
        _assert_vote_matches(
            {k: got[k][gi] for k in got}, want, tie, tag=f"[{gi}]"
        )


def test_vote_groups_empty_columns(rng):
    bases = np.full((2, 4, 16), NBASE, dtype=np.int8)
    quals = np.zeros((2, 4, 16), dtype=np.float32)
    out = column_vote_groups(bases, quals, ConsensusParams(), interpret=True)
    assert (np.asarray(out["base"]) == NBASE).all()
    assert (np.asarray(out["depth"]) == 0).all()
    assert (np.asarray(out["errors"]) == 0).all()


def test_vote_groups_min_quality_filter(rng):
    bases, quals = _random_groups(rng, 4, 6, 24)
    params = ConsensusParams(min_input_base_quality=20)
    got = column_vote_groups(bases, quals, params, interpret=True)
    for gi in range(4):
        want = column_vote(bases[gi], quals[gi], params)
        tie = _tie_columns(bases[gi], quals[gi], params)
        _assert_vote_matches(
            {k: got[k][gi] for k in got}, want, tie, tag=f"[{gi}]"
        )


@pytest.mark.parametrize("f,t,w", [(2, 3, 48), (5, 17, 160)])
def test_molecular_pallas_matches_xla(rng, f, t, w):
    bases = rng.integers(0, 5, size=(f, t, 2, w)).astype(np.int8)
    cover = rng.random((f, t, 2, w)) < 0.7
    bases[~cover] = NBASE
    quals = np.where(bases != NBASE, rng.integers(2, 41, size=bases.shape), 0).astype(
        np.uint8
    )
    params = ConsensusParams()
    got = molecular_consensus_pallas(bases, quals, params, interpret=True)
    want = molecular_consensus(bases, quals, params)
    # tie columns (ambiguous argmax) per family x role, on the cocalled data
    from bsseqconsensusreads_tpu.models.molecular import overlap_cocall
    import jax

    cb, cq = jax.vmap(overlap_cocall)(
        np.asarray(bases), np.asarray(quals, dtype=np.float32)
    )
    cb, cq = np.asarray(cb), np.asarray(cq)
    for k in want:
        assert got[k].dtype == want[k].dtype, k
    for fi in range(f):
        for role in range(2):
            want_r = {k: np.asarray(want[k])[fi, role] for k in want}
            got_r = {k: np.asarray(got[k])[fi, role] for k in got}
            tie = _tie_columns(cb[fi, :, role], cq[fi, :, role], params)
            _assert_vote_matches(got_r, want_r, tie, tag=f"[{fi},{role}]")


@pytest.mark.parametrize("f,w", [(5, 64), (11, 130)])
def test_duplex_pallas_matches_xla(rng, f, w):
    """duplex_consensus_pallas vs models.duplex.duplex_consensus: same
    tie-aware comparison as the molecular kernel (duplex depth is 2, so
    disagreeing strands of equal quality tie by construction)."""
    from bsseqconsensusreads_tpu.models.duplex import duplex_consensus
    from bsseqconsensusreads_tpu.ops.pallas_vote import duplex_consensus_pallas

    bases, quals = _random_groups(rng, f, 4, w)
    params = ConsensusParams(min_reads=0)
    got = duplex_consensus_pallas(bases, quals, params, interpret=True)
    want = duplex_consensus(bases, quals, params)
    pair_rows = ((0, 1), (2, 3))
    for fi in range(f):
        for role, rows in enumerate(pair_rows):
            tie = _tie_columns(bases[fi, list(rows)], quals[fi, list(rows)], params)
            _assert_vote_matches(
                {k: np.asarray(got[k])[fi, role] for k in
                 ("base", "qual", "depth", "errors")},
                {k: np.asarray(want[k])[fi, role] for k in
                 ("base", "qual", "depth", "errors")},
                tie, tag=f"[{fi},{role}]",
            )
    for k in ("a_depth", "b_depth"):
        np.testing.assert_array_equal(np.asarray(got[k]), np.asarray(want[k]), err_msg=k)


def test_duplex_pipeline_pallas_kernel_end_to_end(rng):
    """The fused duplex pipeline with vote_kernel='pallas' agrees with the
    xla kernel on real (non-tie-heavy) duplex family windows."""
    from bsseqconsensusreads_tpu.models.duplex import duplex_call_pipeline

    f, w = 6, 96
    bases = rng.integers(0, 4, size=(f, 4, w)).astype(np.int8)
    cover = np.zeros((f, 4, w), dtype=bool)
    cover[:, :, 4 : w - 4] = True
    bases[~cover] = NBASE
    # identical strand pairs: no vote ties, exact agreement expected
    bases[:, 1] = bases[:, 0]
    bases[:, 3] = bases[:, 2]
    quals = np.where(cover, rng.integers(10, 41, size=(f, 4, w)), 0).astype(np.float32)
    ref = rng.integers(0, 4, size=(f, w + 1)).astype(np.int8)
    cmask = np.zeros((f, 4), dtype=bool)
    cmask[:, 1] = cmask[:, 2] = True
    elig = np.ones(f, dtype=bool)
    params = ConsensusParams(min_reads=0)
    out_x = duplex_call_pipeline(bases, quals, cover, ref, cmask, elig,
                                 params=params, vote_kernel="xla")
    out_p = duplex_call_pipeline(bases, quals, cover, ref, cmask, elig,
                                 params=params, vote_kernel="pallas")
    for k in ("base", "depth", "errors", "a_depth", "b_depth", "la", "rd"):
        np.testing.assert_array_equal(
            np.asarray(out_x[k]), np.asarray(out_p[k]), err_msg=k
        )
    assert (np.abs(np.asarray(out_x["qual"]).astype(int)
                   - np.asarray(out_p["qual"]).astype(int)) <= 1).all()
