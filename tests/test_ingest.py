"""Columnar ingest (pipeline.ingest): the native decoder path must produce
byte-identical stage output to the Python BamReader path, and its
ingest-phase throughput must beat it (the VERDICT round-1 item 10
before/after measurement, recorded in StageStats.metrics)."""

import os
import time

import numpy as np
import pytest

from bsseqconsensusreads_tpu.io.bam import BamReader, BamWriter
from bsseqconsensusreads_tpu.pipeline import ingest
from bsseqconsensusreads_tpu.pipeline.calling import (
    StageStats,
    call_molecular_batches,
)
from bsseqconsensusreads_tpu.utils.testing import (
    make_grouped_bam_records,
    random_genome,
)

pytestmark = pytest.mark.skipif(
    not ingest.available(), reason="native decoder not built"
)


@pytest.fixture(scope="module")
def ingest_bam(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("ingest")
    rng = np.random.default_rng(41)
    name, genome = random_genome(rng, 50000)
    header, records = make_grouped_bam_records(
        rng, name, genome, n_families=800, read_len=80
    )
    path = str(tmp / "in.bam")
    with BamWriter(path, header) as w:
        w.write_all(records)
    return {"path": path, "n_records": len(records), "header": header}


def _run(source, stats=None):
    stats = stats or StageStats()
    out = [
        rec
        for b in call_molecular_batches(
            source, mode="self", grouping="coordinate", stats=stats, mesh=None
        )
        for rec in b
    ]
    return out, stats


class TestColumnarIngest:
    def test_view_surface_matches_bamrecord(self, ingest_bam):
        with BamReader(ingest_bam["path"]) as r:
            py = list(r)
        nat = list(ingest.columnar_records(ingest_bam["path"]))
        assert len(py) == len(nat)
        for a, b in zip(py, nat):
            assert (a.qname, a.flag, a.ref_id, a.pos, a.mapq) == (
                b.qname, b.flag, b.ref_id, b.pos, b.mapq
            )
            assert (a.next_ref_id, a.next_pos, a.tlen) == (
                b.next_ref_id, b.next_pos, b.tlen
            )
            assert a.cigar == b.cigar
            assert a.seq == b.seq
            assert a.qual == b.qual
            assert a.reference_end == b.reference_end
            assert str(a.get_tag("MI")) == b.get_tag("MI")
            assert str(a.get_tag("RX")) == b.get_tag("RX")

    def test_stage_output_identical(self, ingest_bam):
        from bsseqconsensusreads_tpu.io.bam import encode_record

        with BamReader(ingest_bam["path"]) as r:
            out_py, _ = _run(r)
        out_nat, stats = _run(ingest.columnar_records(ingest_bam["path"]))
        assert len(out_py) == len(out_nat)
        for a, b in zip(out_py, out_nat):
            assert a.qname == b.qname and a.flag == b.flag and a.pos == b.pos
            # byte-level equality covers seq/qual AND the tag block
            # (emitted tag values may be numpy arrays — _encode_tags
            # serializes them identically to lists)
            assert encode_record(a) == encode_record(b)
        assert "ingest_seconds" in stats.metrics.as_dict()
        assert stats.records_in == ingest_bam["n_records"]

    def test_ingest_phase_speedup(self, ingest_bam):
        """Ingest-phase records/sec (records_in / ingest_seconds): the
        native decoder must not be slower than the Python path. Raw
        iteration measures ~3x faster (522k vs 155k rec/s on this shape);
        the assertion is deliberately loose against CI noise."""

        def phase_rate(mk):
            _run(mk())  # warm jit
            best = 0.0
            for _ in range(2):
                _, stats = _run(mk())
                m = stats.metrics.as_dict()
                best = max(best, stats.records_in / m["ingest_seconds"])
            return best

        py = phase_rate(lambda: BamReader(ingest_bam["path"]))
        nat = phase_rate(
            lambda: ingest.columnar_records(ingest_bam["path"])
        )
        assert nat > py * 0.9, (py, nat)

    def test_pipeline_ingest_knob(self, ingest_bam, tmp_path):
        from bsseqconsensusreads_tpu.pipeline.stages import ingest_records
        from bsseqconsensusreads_tpu.pipeline.workflow import WorkflowError

        stats = StageStats()
        src = ingest_records(
            ingest_bam["path"], None, stats,
            ingest_choice="native", grouping="coordinate",
        )
        # coordinate + native -> the C-side pre-grouped stream
        assert isinstance(src, ingest.GroupedColumnarStream)
        mi, recs = next(src.iter_groups())
        assert isinstance(recs[0], ingest.ColumnarRecordView)
        assert stats.metrics.counters["ingest_native"] == 1
        assert stats.metrics.counters["group_native"] == 1
        # grouping disabled by env -> plain columnar views
        import os as _os

        _os.environ["BSSEQ_TPU_NATIVE_GROUPING"] = "0"
        try:
            stats15 = StageStats()
            src15 = ingest_records(
                ingest_bam["path"], None, stats15,
                ingest_choice="native", grouping="coordinate",
            )
            assert isinstance(next(iter(src15)), ingest.ColumnarRecordView)
            assert stats15.metrics.counters["group_native"] == 0
        finally:
            del _os.environ["BSSEQ_TPU_NATIVE_GROUPING"]
        # explicit native + gather grouping is refused loudly (silent
        # engine downgrades hide what a benchmark actually measured)
        with pytest.raises(WorkflowError, match="gather"):
            ingest_records(
                ingest_bam["path"], None, StageStats(),
                ingest_choice="native", grouping="gather",
            )
        # ... as is explicit native when the stage disallows it (the
        # duplex wrapper names the reason)
        from bsseqconsensusreads_tpu.pipeline.stages import (
            duplex_ingest_stream,
        )

        with pytest.raises(WorkflowError, match="passthrough"):
            duplex_ingest_stream(
                ingest_bam["path"], None, StageStats(),
                ingest_choice="native", passthrough=True,
            )
        # auto + gather falls back to the python reader (buffer pinning)
        stats2 = StageStats()
        with BamReader(ingest_bam["path"]) as r:
            src2 = ingest_records(
                ingest_bam["path"], r, stats2,
                ingest_choice="auto", grouping="gather",
            )
            assert src2 is r
        assert stats2.metrics.counters["ingest_native"] == 0


class TestColumnarEdgeParity:
    """Engine-parity edges the review surfaced: long qnames and missing
    qualities must behave identically on both ingest engines."""

    def _roundtrip(self, tmp_path, records, header):
        path = str(tmp_path / "edge.bam")
        with BamWriter(path, header) as w:
            w.write_all(records)
        with BamReader(path) as r:
            py = list(r)
        nat = list(ingest.columnar_records(path))
        return py, nat

    def test_max_length_qname_not_truncated(self, tmp_path, ingest_bam):
        from bsseqconsensusreads_tpu.io.bam import BamRecord, CMATCH

        # 254 chars is the BAM format maximum (l_read_name uint8)
        long_a = "Q" * 240 + "A" * 14
        long_b = "Q" * 240 + "B" * 14  # same 240-char prefix
        recs = []
        for qn in (long_a, long_b):
            r = BamRecord(qname=qn, flag=99, ref_id=0, pos=10, mapq=60,
                          cigar=[(CMATCH, 4)], seq="ACGT", qual=bytes([30] * 4))
            r.set_tag("MI", "0/A", "Z")
            recs.append(r)
        py, nat = self._roundtrip(tmp_path, recs, ingest_bam["header"])
        assert [r.qname for r in nat] == [long_a, long_b]
        assert [r.qname for r in py] == [r.qname for r in nat]

    def test_missing_quals_zero_not_255(self, tmp_path, ingest_bam):
        from bsseqconsensusreads_tpu.io.bam import BamRecord, CMATCH
        from bsseqconsensusreads_tpu.ops.encode import trim_softclips_keep_indels

        r = BamRecord(qname="noq", flag=99, ref_id=0, pos=10, mapq=60,
                      cigar=[(CMATCH, 4)], seq="ACGT", qual=None)
        r.set_tag("MI", "0/A", "Z")
        py, nat = self._roundtrip(tmp_path, [r], ingest_bam["header"])
        assert py[0].qual is None and nat[0].qual is None
        tp = trim_softclips_keep_indels(py[0])
        tn = trim_softclips_keep_indels(nat[0])
        np.testing.assert_array_equal(tp[1], tn[1])
        assert (tn[1] == 0).all()


def test_cigar_digest_parity_on_clipped_indel_reads(tmp_path):
    """The C-side CIGAR digest (ref_span / left_clip / right_clip /
    cigar_flags) must agree with the Python BamRecord cigar walk on every
    CIGAR class the pipeline branches on: softclips (either/both ends),
    insertions, deletions, refskips, hardclips, and the all-softclip
    degenerate (round-3 review finding: the digest previously had no
    non-pure-M coverage)."""
    import numpy as np

    from bsseqconsensusreads_tpu.io.bam import (
        BamHeader,
        BamReader,
        BamRecord,
        BamWriter,
        CDEL,
        CHARD_CLIP,
        CINS,
        CMATCH,
        CREF_SKIP,
        CSOFT_CLIP,
    )
    from bsseqconsensusreads_tpu.ops.encode import trim_softclips_keep_indels
    from bsseqconsensusreads_tpu.pipeline import ingest

    if not ingest.available():
        pytest.skip("native decoder unavailable")

    cases = [
        [(CMATCH, 20)],
        [(CSOFT_CLIP, 3), (CMATCH, 17)],
        [(CMATCH, 15), (CSOFT_CLIP, 5)],
        [(CSOFT_CLIP, 2), (CMATCH, 14), (CSOFT_CLIP, 4)],
        [(CMATCH, 8), (CINS, 2), (CMATCH, 10)],
        [(CMATCH, 9), (CDEL, 3), (CMATCH, 11)],
        [(CMATCH, 6), (CREF_SKIP, 40), (CMATCH, 14)],
        [(CHARD_CLIP, 5), (CMATCH, 20)],
        [(CMATCH, 18), (CHARD_CLIP, 2)],
        [(CSOFT_CLIP, 20)],  # single all-S: trims to empty on both paths
        [(CSOFT_CLIP, 1), (CMATCH, 10), (CDEL, 2), (CMATCH, 5),
         (CSOFT_CLIP, 4)],
    ]
    rng = np.random.default_rng(17)
    header = BamHeader("@HD\tVN:1.6\n", [("chr1", 100000)])
    records = []
    for i, cig in enumerate(cases):
        read_len = sum(n for op, n in cig if op in (CMATCH, CINS, CSOFT_CLIP))
        seq = "".join("ACGT"[b] for b in rng.integers(0, 4, size=read_len))
        rec = BamRecord(
            qname=f"c{i}", flag=0, ref_id=0, pos=100 + 50 * i, mapq=60,
            cigar=cig, next_ref_id=-1, next_pos=-1, tlen=0,
            seq=seq, qual=bytes(rng.integers(2, 41, size=read_len).tolist()),
        )
        rec.set_tag("MI", f"{i}/A", "Z")
        records.append(rec)
    path = str(tmp_path / "digest.bam")
    with BamWriter(path, header, engine="python") as w:
        w.write_all(records)

    views = list(ingest.columnar_records(path))
    assert len(views) == len(records)
    for rec, view in zip(records, views):
        cig = rec.cigar
        # reference_end parity (grouping sweep input)
        assert view.reference_end == rec.reference_end, cig
        # clip_info parity vs the Python walk
        lclip = cig[0][1] if cig and cig[0][0] == CSOFT_CLIP else 0
        rclip = cig[-1][1] if cig and cig[-1][0] == CSOFT_CLIP else 0
        has_indel = any(op in (CINS, CDEL) for op, _ in cig)
        has_hard = any(op == CHARD_CLIP for op, _ in cig)
        assert view.clip_info == (lclip, rclip, has_indel, has_hard), cig
        # trim fast path == BamRecord slow path
        got = trim_softclips_keep_indels(view)
        want = trim_softclips_keep_indels(rec)
        if want is None:
            assert got is None, cig
        else:
            np.testing.assert_array_equal(got[0], want[0], err_msg=str(cig))
            np.testing.assert_array_equal(got[1], want[1], err_msg=str(cig))
            assert got[2:] == want[2:], cig


def test_messy_cigar_pipeline_parity_columnar_vs_python(tmp_path):
    """Full molecular stage over a clipped/indel/hardclip-bearing BAM:
    columnar ingest (C CIGAR digest fast paths) and the pure-Python
    BamReader path must produce byte-identical output BAMs."""
    import numpy as np

    from bsseqconsensusreads_tpu.io.bam import (
        BamHeader,
        BamRecord,
        BamWriter,
        CDEL,
        CHARD_CLIP,
        CINS,
        CMATCH,
        CSOFT_CLIP,
    )
    from bsseqconsensusreads_tpu.io.bam import write_items
    from bsseqconsensusreads_tpu.pipeline import ingest
    from bsseqconsensusreads_tpu.pipeline.calling import (
        StageStats,
        call_molecular_batches,
    )
    from bsseqconsensusreads_tpu.utils.testing import random_genome

    if not ingest.available():
        pytest.skip("native decoder unavailable")
    rng = np.random.default_rng(29)
    name, genome = random_genome(rng, 3000)
    header = BamHeader("@HD\tVN:1.6\tSO:coordinate\n", [(name, len(genome))])
    records = []
    for fam in range(40):
        start = 20 + fam * 60
        depth = int(rng.integers(1, 5))
        for d in range(depth):
            for flag, pos in ((99, start), (147, start + 30)):
                cig = [(CMATCH, 30)]
                roll = int(rng.integers(0, 6))
                if roll == 0:
                    cig = [(CSOFT_CLIP, 4), (CMATCH, 26)]
                elif roll == 1:
                    cig = [(CMATCH, 26), (CSOFT_CLIP, 4)]
                elif roll == 2:
                    cig = [(CMATCH, 12), (CINS, 2), (CMATCH, 16)]
                elif roll == 3:
                    cig = [(CMATCH, 14), (CDEL, 3), (CMATCH, 16)]
                elif roll == 4:
                    cig = [(CHARD_CLIP, 3), (CMATCH, 30)]
                read_len = sum(n for op, n in cig
                               if op in (CMATCH, CINS, CSOFT_CLIP))
                seq = "".join(
                    "ACGT"[b] for b in rng.integers(0, 4, size=read_len)
                )
                rec = BamRecord(
                    qname=f"f{fam}d{d}", flag=flag, ref_id=0, pos=pos,
                    mapq=60, cigar=cig, next_ref_id=0,
                    next_pos=start + 30 if flag == 99 else start,
                    seq=seq,
                    qual=bytes(rng.integers(2, 41, size=read_len).tolist()),
                )
                rec.set_tag("MI", f"{fam}/A", "Z")
                rec.set_tag("RX", "AC-GT", "Z")
                records.append(rec)
    inp = str(tmp_path / "messy.bam")
    with BamWriter(inp, header) as w:
        w.write_all(records)

    outs = {}
    for engine in ("columnar", "python"):
        from bsseqconsensusreads_tpu.io.bam import BamReader

        stats = StageStats()
        if engine == "columnar":
            stream = ingest.columnar_records(inp)
        else:
            stream = BamReader(inp)
        batches = call_molecular_batches(
            stream, mode="self", grouping="coordinate", stats=stats,
            mesh=None,
        )
        out = str(tmp_path / f"out_{engine}.bam")
        with BamWriter(out, header, engine="python") as w:
            for b in batches:
                write_items(w, b)
        outs[engine] = open(out, "rb").read()
    assert outs["columnar"] == outs["python"] and len(outs["columnar"]) > 100


class TestNativeGrouper:
    """C-side coordinate MI-grouping (io.native.read_grouped_columnar /
    ingest.GroupedColumnarStream) vs the Python streamer: identical groups
    in identical order, same refragmentation accounting, same missing-MI
    error, bounded buffers growing for monster families."""

    def _write(self, tmp_path, records, name="g.bam", refs=(("chr1", 100000),)):
        from bsseqconsensusreads_tpu.io.bam import BamHeader, BamWriter

        path = str(tmp_path / name)
        with BamWriter(path, BamHeader("@HD\tVN:1.6\n", list(refs))) as w:
            w.write_all(records)
        return path

    def _records(self, rng, n_fams=200, dup_every=0):
        from bsseqconsensusreads_tpu.io.bam import BamRecord, CMATCH

        recs = []
        for fam in range(n_fams):
            start = 10 + fam * 13
            for flag, pos in ((99, start), (147, start + 30)):
                r = BamRecord(
                    qname=f"f{fam}", flag=flag, ref_id=0, pos=pos, mapq=60,
                    cigar=[(CMATCH, 25)], next_ref_id=0, next_pos=start,
                    seq="A" * 25, qual=bytes([30] * 25),
                )
                r.set_tag("MI", f"{fam % dup_every if dup_every else fam}/A", "Z")
                recs.append(r)
        return recs

    def test_groups_match_python_streamer(self, tmp_path):
        import numpy as np

        from bsseqconsensusreads_tpu.pipeline import ingest
        from bsseqconsensusreads_tpu.pipeline.calling import (
            StageStats,
            stream_mi_groups,
        )

        if not ingest.available():
            pytest.skip("native decoder unavailable")
        rng = np.random.default_rng(3)
        path = self._write(tmp_path, self._records(rng))
        py = [
            (mi, [(r.qname, r.flag, r.pos) for r in recs])
            for mi, recs in stream_mi_groups(
                ingest.columnar_records(path), grouping="coordinate"
            )
        ]
        stats = StageStats()
        nat = [
            (mi, [(r.qname, r.flag, r.pos) for r in recs])
            for mi, recs in stream_mi_groups(
                ingest.GroupedColumnarStream(path),
                grouping="coordinate", stats=stats,
            )
        ]
        assert nat == py  # content AND order
        assert stats.records_in == 400

    def test_refragmentation_counted_like_python(self, tmp_path):
        import numpy as np

        from bsseqconsensusreads_tpu.io.bam import BamRecord, CMATCH
        from bsseqconsensusreads_tpu.pipeline import ingest
        from bsseqconsensusreads_tpu.pipeline.calling import (
            StageStats,
            stream_mi_groups,
        )

        if not ingest.available():
            pytest.skip("native decoder unavailable")
        # same MI at two loci far beyond the flush margin -> refragmented
        recs = []
        for pos in (100, 60_000):
            r = BamRecord(
                qname=f"q{pos}", flag=0, ref_id=0, pos=pos, mapq=60,
                cigar=[(CMATCH, 20)], next_ref_id=-1, next_pos=-1,
                seq="C" * 20, qual=bytes([30] * 20),
            )
            r.set_tag("MI", "77/A", "Z")
            recs.append(r)
        # spacer families so the sweep advances
        for i, pos in enumerate(range(200, 50_000, 400)):
            r = BamRecord(
                qname=f"s{i}", flag=0, ref_id=0, pos=pos, mapq=60,
                cigar=[(CMATCH, 20)], next_ref_id=-1, next_pos=-1,
                seq="G" * 20, qual=bytes([30] * 20),
            )
            r.set_tag("MI", f"s{i}/A", "Z")
            recs.append(r)
        recs.sort(key=lambda r: r.pos)
        path = self._write(tmp_path, recs)
        want_stats = StageStats()
        list(stream_mi_groups(ingest.columnar_records(path),
                              grouping="coordinate", stats=want_stats))
        got_stats = StageStats()
        list(stream_mi_groups(ingest.GroupedColumnarStream(path),
                              grouping="coordinate", stats=got_stats))
        assert want_stats.refragmented_families == 1
        assert got_stats.refragmented_families == 1

    def test_missing_mi_raises(self, tmp_path):
        import numpy as np

        from bsseqconsensusreads_tpu.io.bam import BamRecord, CMATCH
        from bsseqconsensusreads_tpu.pipeline import ingest
        from bsseqconsensusreads_tpu.pipeline.calling import stream_mi_groups

        if not ingest.available():
            pytest.skip("native decoder unavailable")
        r = BamRecord(
            qname="nomi", flag=0, ref_id=0, pos=5, mapq=60,
            cigar=[(CMATCH, 10)], next_ref_id=-1, next_pos=-1,
            seq="A" * 10, qual=bytes([30] * 10),
        )
        path = self._write(tmp_path, [r])
        with pytest.raises(ValueError, match="nomi does not have MI tag"):
            list(stream_mi_groups(ingest.GroupedColumnarStream(path),
                                  grouping="coordinate"))

    def test_monster_family_grows_buffers(self, tmp_path):
        import numpy as np

        from bsseqconsensusreads_tpu.io import native
        from bsseqconsensusreads_tpu.io.bam import BamRecord, CMATCH

        if not native.available():
            pytest.skip("native decoder unavailable")
        # one family whose record count exceeds the initial batch cap
        recs = []
        for d in range(300):
            r = BamRecord(
                qname=f"t{d}", flag=0, ref_id=0, pos=50, mapq=60,
                cigar=[(CMATCH, 30)], next_ref_id=-1, next_pos=-1,
                seq="T" * 30, qual=bytes([30] * 30),
            )
            r.set_tag("MI", "0/A", "Z")
            recs.append(r)
        path = self._write(tmp_path, recs)
        out = list(native.read_grouped_columnar(path, batch_records=64))
        total = sum(int(fn.sum()) for _, _, fn, _ in out)
        assert total == 300
        assert all(len(fm) >= 1 for _, fm, _, _ in out)

    def test_config_mismatch_rejected(self, tmp_path):
        import numpy as np

        from bsseqconsensusreads_tpu.pipeline import ingest
        from bsseqconsensusreads_tpu.pipeline.calling import stream_mi_groups

        if not ingest.available():
            pytest.skip("native decoder unavailable")
        rng = np.random.default_rng(4)
        path = self._write(tmp_path, self._records(rng, n_fams=3))
        with pytest.raises(ValueError, match="pre-grouped"):
            list(stream_mi_groups(ingest.GroupedColumnarStream(path),
                                  grouping="adjacent"))
        with pytest.raises(ValueError, match="strip_suffix"):
            list(stream_mi_groups(
                ingest.GroupedColumnarStream(path, strip_suffix=True),
                grouping="coordinate",
            ))


def test_grouper_empty_mi_after_strip_groups_not_errors(tmp_path):
    """MI '/A' strips to the empty key: the Python streamer groups under ''
    — the native grouper must too, not abort as missing-MI (round-3 review
    finding: absent tag vs empty value)."""
    from bsseqconsensusreads_tpu.io.bam import BamHeader, BamRecord, BamWriter, CMATCH
    from bsseqconsensusreads_tpu.pipeline import ingest
    from bsseqconsensusreads_tpu.pipeline.calling import stream_mi_groups

    if not ingest.available():
        pytest.skip("native decoder unavailable")
    r = BamRecord(
        qname="edge", flag=0, ref_id=0, pos=5, mapq=60,
        cigar=[(CMATCH, 10)], next_ref_id=-1, next_pos=-1,
        seq="A" * 10, qual=bytes([30] * 10),
    )
    r.set_tag("MI", "/A", "Z")
    path = str(tmp_path / "e.bam")
    with BamWriter(path, BamHeader("@HD\tVN:1.6\n", [("chr1", 1000)])) as w:
        w.write(r)
    groups = list(stream_mi_groups(
        ingest.GroupedColumnarStream(path, strip_suffix=True),
        grouping="coordinate", strip_suffix=True,
    ))
    assert len(groups) == 1 and groups[0][0] == ""
    assert len(groups[0][1]) == 1
