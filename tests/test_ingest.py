"""Columnar ingest (pipeline.ingest): the native decoder path must produce
byte-identical stage output to the Python BamReader path, and its
ingest-phase throughput must beat it (the VERDICT round-1 item 10
before/after measurement, recorded in StageStats.metrics)."""

import os
import time

import numpy as np
import pytest

from bsseqconsensusreads_tpu.io.bam import BamReader, BamWriter
from bsseqconsensusreads_tpu.pipeline import ingest
from bsseqconsensusreads_tpu.pipeline.calling import (
    StageStats,
    call_molecular_batches,
)
from bsseqconsensusreads_tpu.utils.testing import (
    make_grouped_bam_records,
    random_genome,
)

pytestmark = pytest.mark.skipif(
    not ingest.available(), reason="native decoder not built"
)


@pytest.fixture(scope="module")
def ingest_bam(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("ingest")
    rng = np.random.default_rng(41)
    name, genome = random_genome(rng, 50000)
    header, records = make_grouped_bam_records(
        rng, name, genome, n_families=800, read_len=80
    )
    path = str(tmp / "in.bam")
    with BamWriter(path, header) as w:
        w.write_all(records)
    return {"path": path, "n_records": len(records), "header": header}


def _run(source, stats=None):
    stats = stats or StageStats()
    out = [
        rec
        for b in call_molecular_batches(
            source, mode="self", grouping="coordinate", stats=stats, mesh=None
        )
        for rec in b
    ]
    return out, stats


class TestColumnarIngest:
    def test_view_surface_matches_bamrecord(self, ingest_bam):
        with BamReader(ingest_bam["path"]) as r:
            py = list(r)
        nat = list(ingest.columnar_records(ingest_bam["path"]))
        assert len(py) == len(nat)
        for a, b in zip(py, nat):
            assert (a.qname, a.flag, a.ref_id, a.pos, a.mapq) == (
                b.qname, b.flag, b.ref_id, b.pos, b.mapq
            )
            assert (a.next_ref_id, a.next_pos, a.tlen) == (
                b.next_ref_id, b.next_pos, b.tlen
            )
            assert a.cigar == b.cigar
            assert a.seq == b.seq
            assert a.qual == b.qual
            assert a.reference_end == b.reference_end
            assert str(a.get_tag("MI")) == b.get_tag("MI")
            assert str(a.get_tag("RX")) == b.get_tag("RX")

    def test_stage_output_identical(self, ingest_bam):
        with BamReader(ingest_bam["path"]) as r:
            out_py, _ = _run(r)
        out_nat, stats = _run(ingest.columnar_records(ingest_bam["path"]))
        assert len(out_py) == len(out_nat)
        for a, b in zip(out_py, out_nat):
            assert a.qname == b.qname and a.flag == b.flag and a.pos == b.pos
            assert a.seq == b.seq and a.qual == b.qual and a.tags == b.tags
        assert "ingest_seconds" in stats.metrics.as_dict()
        assert stats.records_in == ingest_bam["n_records"]

    def test_ingest_phase_speedup(self, ingest_bam):
        """Ingest-phase records/sec (records_in / ingest_seconds): the
        native decoder must not be slower than the Python path. Raw
        iteration measures ~3x faster (522k vs 155k rec/s on this shape);
        the assertion is deliberately loose against CI noise."""

        def phase_rate(mk):
            _run(mk())  # warm jit
            best = 0.0
            for _ in range(2):
                _, stats = _run(mk())
                m = stats.metrics.as_dict()
                best = max(best, stats.records_in / m["ingest_seconds"])
            return best

        py = phase_rate(lambda: BamReader(ingest_bam["path"]))
        nat = phase_rate(
            lambda: ingest.columnar_records(ingest_bam["path"])
        )
        assert nat > py * 0.9, (py, nat)

    def test_pipeline_ingest_knob(self, ingest_bam, tmp_path):
        from bsseqconsensusreads_tpu.config import FrameworkConfig
        from bsseqconsensusreads_tpu.pipeline.stages import PipelineBuilder

        cfg = FrameworkConfig(ingest="native", grouping="coordinate")
        b = PipelineBuilder(cfg, ingest_bam["path"], str(tmp_path))
        stats = StageStats()
        src = b._ingest_records(ingest_bam["path"], None, stats)
        assert isinstance(next(iter(src)), ingest.ColumnarRecordView)
        assert stats.metrics.counters["ingest_native"] == 1
        # gather grouping forces the python reader (buffer pinning)
        cfg2 = FrameworkConfig(ingest="native", grouping="gather")
        b2 = PipelineBuilder(cfg2, ingest_bam["path"], str(tmp_path))
        stats2 = StageStats()
        with BamReader(ingest_bam["path"]) as r:
            src2 = b2._ingest_records(ingest_bam["path"], r, stats2)
            assert src2 is r
        assert stats2.metrics.counters["ingest_native"] == 0


class TestColumnarEdgeParity:
    """Engine-parity edges the review surfaced: long qnames and missing
    qualities must behave identically on both ingest engines."""

    def _roundtrip(self, tmp_path, records, header):
        path = str(tmp_path / "edge.bam")
        with BamWriter(path, header) as w:
            w.write_all(records)
        with BamReader(path) as r:
            py = list(r)
        nat = list(ingest.columnar_records(path))
        return py, nat

    def test_max_length_qname_not_truncated(self, tmp_path, ingest_bam):
        from bsseqconsensusreads_tpu.io.bam import BamRecord, CMATCH

        # 254 chars is the BAM format maximum (l_read_name uint8)
        long_a = "Q" * 240 + "A" * 14
        long_b = "Q" * 240 + "B" * 14  # same 240-char prefix
        recs = []
        for qn in (long_a, long_b):
            r = BamRecord(qname=qn, flag=99, ref_id=0, pos=10, mapq=60,
                          cigar=[(CMATCH, 4)], seq="ACGT", qual=bytes([30] * 4))
            r.set_tag("MI", "0/A", "Z")
            recs.append(r)
        py, nat = self._roundtrip(tmp_path, recs, ingest_bam["header"])
        assert [r.qname for r in nat] == [long_a, long_b]
        assert [r.qname for r in py] == [r.qname for r in nat]

    def test_missing_quals_zero_not_255(self, tmp_path, ingest_bam):
        from bsseqconsensusreads_tpu.io.bam import BamRecord, CMATCH
        from bsseqconsensusreads_tpu.ops.encode import trim_softclips_keep_indels

        r = BamRecord(qname="noq", flag=99, ref_id=0, pos=10, mapq=60,
                      cigar=[(CMATCH, 4)], seq="ACGT", qual=None)
        r.set_tag("MI", "0/A", "Z")
        py, nat = self._roundtrip(tmp_path, [r], ingest_bam["header"])
        assert py[0].qual is None and nat[0].qual is None
        tp = trim_softclips_keep_indels(py[0])
        tn = trim_softclips_keep_indels(nat[0])
        np.testing.assert_array_equal(tp[1], tn[1])
        assert (tn[1] == 0).all()
