"""ops.hosttwin numpy twins vs the jit window transforms.

The duplex raw-unit accounting trusts these twins to reproduce the device
transform exactly (strand call planes for ac/bc tags, the raw->converted
base map for exact ce). Any drift is silent tag corruption, so equality is
pinned bit-for-bit on randomized batches covering prepends, CpG pair
context, trailing trims, missing rows, and ineligible families.
"""

from __future__ import annotations

import numpy as np
import pytest

from bsseqconsensusreads_tpu.alphabet import NBASE
from bsseqconsensusreads_tpu.ops import hosttwin
from bsseqconsensusreads_tpu.ops.convert import convert_ag_to_ct
from bsseqconsensusreads_tpu.ops.extend import extend_gap


def _random_batch(rng, f=40, w=48):
    bases = np.full((f, 4, w), NBASE, np.int8)
    cover = np.zeros((f, 4, w), bool)
    quals = np.zeros((f, 4, w), np.uint8)
    for fi in range(f):
        for r in range(4):
            if rng.random() < 0.12:
                continue  # missing row
            start = int(rng.integers(0, w // 2))
            length = int(rng.integers(1, w - start))
            bases[fi, r, start : start + length] = rng.integers(
                0, 4, size=length
            )
            quals[fi, r, start : start + length] = rng.integers(
                2, 41, size=length
            )
            cover[fi, r, start : start + length] = True
    ref = rng.integers(0, 4, size=(f, w + 1)).astype(np.int8)
    convert_mask = np.zeros((f, 4), bool)
    convert_mask[:, 1] = convert_mask[:, 2] = True
    eligible = rng.random(f) < 0.8
    return bases, quals, cover, ref, convert_mask, eligible


@pytest.fixture(scope="module")
def batch():
    return _random_batch(np.random.default_rng(77))


class TestHostTwins:
    def test_convert_twin_matches_jit(self, batch):
        bases, quals, cover, ref, cmask, _ = batch
        jb, _jq, jc, jla, jrd = (
            np.asarray(x)
            for x in convert_ag_to_ct(bases, quals, cover, ref, cmask)
        )
        tb, tc, tla, trd = hosttwin.convert_np(bases, cover, ref, cmask)
        np.testing.assert_array_equal(tc, jc)
        np.testing.assert_array_equal(
            np.where(tc, tb, NBASE), np.where(jc, jb, NBASE)
        )
        np.testing.assert_array_equal(tla, jla)
        np.testing.assert_array_equal(trd, jrd)

    def test_extend_twin_matches_jit(self, batch):
        bases, quals, cover, ref, cmask, eligible = batch
        jb, jq, jc, jla, jrd = convert_ag_to_ct(bases, quals, cover, ref, cmask)
        eb, _eq, ec = (
            np.asarray(x) for x in extend_gap(jb, jq, jc, jla, jrd, eligible)
        )
        tb0, tc0, tla, trd = hosttwin.convert_np(bases, cover, ref, cmask)
        tb, tc = hosttwin.extend_np(tb0, tc0, tla, trd, eligible)
        np.testing.assert_array_equal(tc, ec)
        np.testing.assert_array_equal(
            np.where(tc, tb, NBASE), np.where(ec, eb, NBASE)
        )

    def test_strand_call_planes_compose(self, batch):
        bases, quals, cover, ref, cmask, eligible = batch
        jb, jq, jc, jla, jrd = convert_ag_to_ct(bases, quals, cover, ref, cmask)
        eb, _eq, ec = (
            np.asarray(x) for x in extend_gap(jb, jq, jc, jla, jrd, eligible)
        )
        calls, ccov = hosttwin.strand_call_planes(
            bases, cover, ref, cmask, eligible
        )
        np.testing.assert_array_equal(ccov, ec)
        np.testing.assert_array_equal(calls, np.where(ec, eb, NBASE))

    def test_conv_base_map_agrees_with_transform(self, batch):
        """For every covered column, pushing the ACTUAL raw base through
        the map must equal the converted base the transform produced
        (pre-extend, pre-trim: the map models the rewrite rule only)."""
        bases, quals, cover, ref, cmask, _ = batch
        m = hosttwin.conv_base_map(bases, cover, ref, cmask)
        jb, _jq, jc, _la, _rd = (
            np.asarray(x)
            for x in convert_ag_to_ct(bases, quals, cover, ref, cmask)
        )
        f, r, w = bases.shape
        mapped = np.take_along_axis(
            m.transpose(1, 2, 3, 0),  # [F, R, W, 4]
            np.clip(bases, 0, 3)[..., None].astype(np.int64),
            axis=-1,
        )[..., 0]
        # compare on raw covered columns that survived (not trimmed) —
        # prepend columns are synthetic (no raw base to map)
        keep = cover & jc & (bases != NBASE)
        np.testing.assert_array_equal(mapped[keep], jb[keep])

    def test_conv_base_map_identity_off_convert_rows(self, batch):
        bases, _quals, cover, ref, cmask, _ = batch
        m = hosttwin.conv_base_map(bases, cover, ref, cmask)
        for x in range(4):
            np.testing.assert_array_equal(
                m[x][:, [0, 3], :], np.full_like(m[x][:, [0, 3], :], x)
            )
