"""Gap extension + duplex merge + fused duplex pipeline tests."""

import numpy as np
import pytest

from bsseqconsensusreads_tpu.alphabet import NBASE
from bsseqconsensusreads_tpu.io.fasta import FastaFile
from bsseqconsensusreads_tpu.models.duplex import duplex_call_pipeline, duplex_consensus
from bsseqconsensusreads_tpu.models.params import ConsensusParams
from bsseqconsensusreads_tpu.ops.convert import convert_ag_to_ct
from bsseqconsensusreads_tpu.ops.encode import (
    codes_to_seq,
    encode_duplex_families,
    iter_mi_groups,
)
from bsseqconsensusreads_tpu.ops.extend import extend_gap
from bsseqconsensusreads_tpu.utils.oracle import (
    oracle_column_vote,
    oracle_convert_read,
    oracle_extend_group,
)
from bsseqconsensusreads_tpu.utils.testing import (
    bisulfite_convert,
    make_aligned_duplex_group,
    random_genome,
    write_fasta,
)

DUPLEX_PARAMS = ConsensusParams(min_reads=0)
FLAG_ROW = {99: 0, 163: 1, 83: 2, 147: 3}


def encode_groups(rng, genome, name, n=6, softclip=0):
    recs = []
    for mi in range(n):
        start = 20 + mi * 120
        recs += make_aligned_duplex_group(
            rng, name, genome, mi, start, 80, softclip=softclip
        )
    groups = iter_mi_groups(recs, strip_suffix=True)
    fa_like = lambda nm, s, e: genome[s:e]
    return encode_duplex_families(groups, fa_like, [name])


def rows_to_records(batch, fi):
    """Extract per-row (seq, qual, pos) from a batch for oracle comparison."""
    out = {}
    for flag, row in FLAG_ROW.items():
        cov = batch.cover[fi, row]
        if not cov.any():
            continue
        idx = np.nonzero(cov)[0]
        out[flag] = {
            "seq": codes_to_seq(batch.bases[fi, row, idx]),
            "qual": [int(q) for q in batch.quals[fi, row, idx]],
            "pos": batch.meta[fi].window_start + int(idx[0]),
        }
    return out


class TestExtendVsOracle:
    def test_full_group_matches_oracle(self):
        rng = np.random.default_rng(11)
        name, genome = random_genome(rng, 1200)
        batch, leftovers, skipped = encode_groups(rng, genome, name)
        assert not leftovers and not skipped
        b, q, c, la, rd = convert_ag_to_ct(
            batch.bases, batch.quals, batch.cover, batch.ref, batch.convert_mask
        )
        b, q, c = np.asarray(b), np.asarray(q), np.asarray(c)
        la, rd = np.asarray(la), np.asarray(rd)
        eb, eq, ec = extend_gap(b, q, c, la, rd)
        eb, eq, ec = np.asarray(eb), np.asarray(eq), np.asarray(ec)
        for fi in range(len(batch.meta)):
            # build oracle inputs from the converted (pre-extend) tensors
            conv = {"bases": b, "quals": q, "cover": c}
            reads = {}
            for flag, row in FLAG_ROW.items():
                cov = c[fi, row]
                if not cov.any():
                    continue
                idx = np.nonzero(cov)[0]
                reads[flag] = {
                    "seq": codes_to_seq(b[fi, row, idx]),
                    "qual": [int(v) for v in q[fi, row, idx]],
                    "pos": batch.meta[fi].window_start + int(idx[0]),
                    "la": int(la[fi, row]),
                    "rd": int(rd[fi, row]),
                }
            want = oracle_extend_group(reads)
            for flag, row in FLAG_ROW.items():
                if flag not in want:
                    continue
                cov = ec[fi, row]
                idx = np.nonzero(cov)[0]
                got_seq = codes_to_seq(eb[fi, row, idx])
                got_pos = batch.meta[fi].window_start + int(idx[0])
                assert got_seq == want[flag]["seq"], f"family {fi} flag {flag}"
                assert got_pos == want[flag]["pos"]
                assert [int(v) for v in eq[fi, row, idx]] == want[flag]["qual"]

    def test_postcondition_identical_spans(self):
        # After extension, both reads of each pair span the same columns
        # (the property TemplateCoordinate sorting relies on, SURVEY §3.3).
        rng = np.random.default_rng(12)
        name, genome = random_genome(rng, 1200)
        batch, _, _ = encode_groups(rng, genome, name)
        b, q, c, la, rd = convert_ag_to_ct(
            batch.bases, batch.quals, batch.cover, batch.ref, batch.convert_mask
        )
        _, _, ec = extend_gap(b, q, c, la, rd)
        ec = np.asarray(ec)
        for fi in range(len(batch.meta)):
            for l_row, r_row in ((1, 0), (2, 3)):
                li = np.nonzero(ec[fi, l_row])[0]
                ri = np.nonzero(ec[fi, r_row])[0]
                if len(li) == 0 or len(ri) == 0:
                    continue
                assert li[0] == ri[0], f"family {fi} pair start mismatch"
                assert li[-1] == ri[-1], f"family {fi} pair end mismatch"

    def test_non_four_read_group_not_extended(self):
        # Reference gate: only exactly-4-read groups are harmonized
        # (tools/2.extend_gap.py:114-115). A 2-read group must pass through.
        rng = np.random.default_rng(21)
        name, genome = random_genome(rng, 600)
        recs = [
            r
            for r in make_aligned_duplex_group(rng, name, genome, 0, 100, 60)
            if r.flag in (99, 163)
        ]
        groups = iter_mi_groups(recs, strip_suffix=True)
        batch, _, _ = encode_duplex_families(groups, lambda n, s, e: genome[s:e], [name])
        assert not batch.extend_eligible[0]
        b, q, c, la, rd = convert_ag_to_ct(
            batch.bases, batch.quals, batch.cover, batch.ref, batch.convert_mask
        )
        eb, eq, ec = extend_gap(b, q, c, la, rd, batch.extend_eligible)
        np.testing.assert_array_equal(np.asarray(ec), np.asarray(c))

    def test_missing_partner_is_noop(self):
        # Family with only the converted read: extension must not invent data.
        W = 128
        bases = np.full((1, 4, W), NBASE, np.int8)
        quals = np.zeros((1, 4, W), np.float32)
        cover = np.zeros((1, 4, W), bool)
        bases[0, 1, 10:20] = 1
        cover[0, 1, 10:20] = True
        la = np.zeros((1, 4), np.int8)
        rd = np.zeros((1, 4), np.int8)
        la[0, 1] = 1
        eb, eq, ec = extend_gap(bases, quals, cover, la, rd)
        np.testing.assert_array_equal(np.asarray(ec), cover)


class TestDuplexMerge:
    def test_agreement_and_disagreement_match_oracle(self):
        rng = np.random.default_rng(13)
        W = 128
        bases = rng.integers(0, 4, size=(3, 4, W)).astype(np.int8)
        quals = rng.integers(10, 41, size=(3, 4, W)).astype(np.float32)
        out = duplex_consensus(bases, quals, DUPLEX_PARAMS)
        for fi in range(3):
            for role, rows in ((0, (0, 1)), (1, (2, 3))):
                for w in range(0, W, 17):
                    col_b = [int(bases[fi, r, w]) for r in rows]
                    col_q = [float(quals[fi, r, w]) for r in rows]
                    wb, wq, wd, we = oracle_column_vote(col_b, col_q)
                    assert int(np.asarray(out["base"])[fi, role, w]) == wb
                    assert int(np.asarray(out["depth"])[fi, role, w]) == wd

    def test_packed_roundtrip_with_quality_filter(self):
        # b_depth = depth - a_depth must hold under min_input_base_quality:
        # a column whose only base is a low-qual A-strand one must not
        # produce a negative b_depth through the packed wire format.
        from bsseqconsensusreads_tpu.models.duplex import (
            duplex_call_pipeline_packed,
            unpack_duplex_outputs,
        )

        W = 128
        bases = np.full((1, 4, W), NBASE, np.int8)
        quals = np.zeros((1, 4, W), np.float32)
        cover = np.zeros((1, 4, W), bool)
        bases[0, 0, :10] = 0
        quals[0, 0, :10] = 5.0  # below the filter
        cover[0, 0, :10] = True
        ref = np.full((1, W + 1), NBASE, np.int8)
        cm = np.zeros((1, 4), bool)
        el = np.ones(1, bool)
        params = ConsensusParams(min_reads=0, min_input_base_quality=20)
        packed, la, rd = duplex_call_pipeline_packed(
            bases, quals, cover, ref, cm, el, params=params
        )
        out = unpack_duplex_outputs(np.asarray(packed), f=1, w=W)
        assert (out["b_depth"] >= 0).all()
        assert (out["a_depth"] == 0).all()  # filtered out of the vote
        assert (out["depth"][0, 0, :10] == 0).all()

    def test_single_strand_family_emits(self):
        # min-reads=0 semantics: one strand only still produces output.
        W = 128
        bases = np.full((1, 4, W), NBASE, np.int8)
        quals = np.zeros((1, 4, W), np.float32)
        bases[0, 0, :30] = 2
        quals[0, 0, :30] = 30.0
        out = duplex_consensus(bases, quals, DUPLEX_PARAMS)
        assert (np.asarray(out["base"])[0, 0, :30] == 2).all()
        assert (np.asarray(out["a_depth"])[0, 0, :30] == 1).all()
        assert (np.asarray(out["b_depth"])[0, 0, :30] == 0).all()


class TestFusedPipeline:
    def test_error_free_duplex_recovers_ct_genome(self):
        # Error-free methylated duplex groups: the fused convert+extend+merge
        # must reproduce the A-strand bisulfite pattern exactly, full depth 2.
        rng = np.random.default_rng(14)
        name, genome = random_genome(rng, 1500)
        batch, leftovers, skipped = encode_groups(rng, genome, name, n=8)
        assert not leftovers and not skipped
        out = duplex_call_pipeline(
            batch.bases, batch.quals, batch.cover, batch.ref, batch.convert_mask,
            batch.extend_eligible, params=DUPLEX_PARAMS,
        )
        base = np.asarray(out["base"])
        depth = np.asarray(out["depth"])
        for fi, meta in enumerate(batch.meta):
            start = meta.window_start
            expect = bisulfite_convert(
                genome[start : start + base.shape[-1]], genome, start, "A"
            )
            for role in range(2):
                cov = np.nonzero(depth[fi, role] > 0)[0]
                assert len(cov) > 0
                got = codes_to_seq(base[fi, role, cov])
                want = "".join(expect[i] for i in cov)
                assert got == want, f"family {fi} role {role}"
                # interior columns see both strands
                assert (depth[fi, role, cov[1:-1]] == 2).all()

    def test_softclipped_inputs_handled(self):
        rng = np.random.default_rng(15)
        name, genome = random_genome(rng, 1500)
        batch, leftovers, skipped = encode_groups(rng, genome, name, n=4, softclip=5)
        assert not skipped
        out = duplex_call_pipeline(
            batch.bases, batch.quals, batch.cover, batch.ref, batch.convert_mask,
            batch.extend_eligible, params=DUPLEX_PARAMS,
        )
        assert np.isfinite(np.asarray(out["qual"], np.float32)).all()

    def test_fasta_backed_ref_fetch(self, tmp_path):
        rng = np.random.default_rng(16)
        name, genome = random_genome(rng, 900)
        path = str(tmp_path / "g.fa")
        write_fasta(path, name, genome)
        fa = FastaFile(path)
        recs = make_aligned_duplex_group(rng, name, genome, 0, 50, 60)
        groups = iter_mi_groups(recs, strip_suffix=True)
        batch, _, _ = encode_duplex_families(groups, fa.fetch, [name])
        # fetched reference must cover the family window + 1 lookahead column;
        # columns beyond that stay N (never read by the kernels)
        start = batch.meta[0].window_start
        cov = np.nonzero(batch.cover[0].any(axis=0))[0]
        window_end = int(cov[-1]) + 1
        want = genome[start : start + window_end + 1]
        assert codes_to_seq(batch.ref[0][: len(want)]) == want


class TestRawStrandDepths:
    """VERDICT r3 item 4: duplex output carries RAW per-strand read depths
    (fgbio units) threaded from the molecular stage's cd/ce tags, so
    fgbio-style `-M 3 2 1` filtering works on duplex BAMs."""

    def _chain(self, seed=20260731, n_families=3, reads_per_strand=(3, 4)):
        from bsseqconsensusreads_tpu.pipeline.calling import (
            call_duplex,
            call_molecular,
        )
        from bsseqconsensusreads_tpu.utils.testing import (
            make_grouped_bam_records,
        )

        local = np.random.default_rng(seed)
        name, genome = random_genome(local, 3000)
        _, records = make_grouped_bam_records(
            local, name, genome, n_families=n_families,
            reads_per_strand=reads_per_strand,
        )
        molecular = list(call_molecular(records, mode="self"))
        assert molecular

        def fetch(_name, start, end):
            return genome[start:end]

        duplex = list(call_duplex(
            iter(molecular), fetch, [name], mode="self",
        ))
        assert duplex
        return molecular, duplex

    def test_ad_bd_carry_raw_molecular_depths(self):
        molecular, duplex = self._chain()
        mol_by = {}
        for rec in molecular:
            mi, strand = str(rec.get_tag("MI")).split("/")
            mol_by[(mi, strand, rec.flag & 0xC0)] = rec
        checked = 0
        for rec in duplex:
            role_bit = rec.flag & 0xC0  # FREAD1 / FREAD2
            _sub, ad = rec.get_tag("ad")
            _sub, bd = rec.get_tag("bd")
            _sub, cd = rec.get_tag("cd")
            ad, bd, cd = (np.asarray(x, np.int64) for x in (ad, bd, cd))
            # raw units: with 3-4 raw reads per strand, presence units (<=1)
            # are impossible
            assert ad.max() >= 3 and bd.max() >= 3
            assert int(rec.get_tag("aD")) == ad.max()
            assert int(rec.get_tag("bD")) == bd.max()
            np.testing.assert_array_equal(cd, ad + bd)
            assert int(rec.get_tag("cD")) == cd.max()
            # the A strand's per-base values come from the A molecular
            # consensus read's own cd array (same MI, strand A, same role),
            # compared over the genomic overlap (convert/extend shift the
            # duplex span by a column at the edges)
            mi = rec.qname
            a_mol = mol_by.get((mi, "A", role_bit))
            if a_mol is None:
                continue
            _sub, a_cd = a_mol.get_tag("cd")
            a_cd = np.asarray(a_cd, np.int64)
            lo = max(rec.pos, a_mol.pos)
            hi = min(rec.pos + len(ad), a_mol.pos + len(a_cd))
            assert hi > lo
            np.testing.assert_array_equal(
                ad[lo - rec.pos : hi - rec.pos],
                a_cd[lo - a_mol.pos : hi - a_mol.pos],
            )
            checked += 1
        assert checked > 0

    def test_fgbio_style_m321_filter_works_on_duplex(self):
        from bsseqconsensusreads_tpu.pipeline.filter import (
            FilterParams,
            FilterStats,
            filter_consensus,
        )
        from bsseqconsensusreads_tpu.pipeline.record_ops import name_sort

        _, duplex = self._chain(n_families=4)
        recs = name_sort(duplex)
        permissive = FilterParams(
            min_reads=(3, 2, 1), max_read_error_rate=1.0,
            max_base_error_rate=1.0, min_base_quality=0,
            max_no_call_fraction=1.0,
        )
        stats = FilterStats()
        kept = list(filter_consensus(recs, permissive, stats))
        # every family has >=3 raw reads per strand: -M 3 2 1 keeps all —
        # impossible under the old presence units (ad/bd capped at 1)
        assert len(kept) == len(recs)
        tight = FilterParams(
            min_reads=(99, 99, 99), max_read_error_rate=1.0,
            max_base_error_rate=1.0, min_base_quality=0,
            max_no_call_fraction=1.0,
        )
        stats2 = FilterStats()
        assert list(filter_consensus(recs, tight, stats2)) == []
        assert stats2.dropped_depth == stats2.templates

    def test_refragmented_family_keeps_raw_depths(self):
        """A refragmented family (same MI twice in one chunk, fragments
        >flush-margin apart) must not cross-wire the cd/ce sidecar: each
        fragment's duplex records keep their own raw depths (r4 review
        finding — the first fragment's records used to vanish)."""
        from bsseqconsensusreads_tpu.pipeline.calling import (
            StageStats,
            call_duplex,
            call_molecular,
        )
        from bsseqconsensusreads_tpu.utils.testing import (
            make_grouped_bam_records,
        )

        local = np.random.default_rng(7)
        name, genome = random_genome(local, 30_000)
        _, records = make_grouped_bam_records(
            local, name, genome, n_families=2, reads_per_strand=(3, 3),
        )
        molecular = list(call_molecular(records, mode="self"))
        fam_mis = sorted({str(r.get_tag("MI")).split("/")[0] for r in molecular})
        assert len(fam_mis) == 2
        shifted = []
        for rec in molecular:
            r = rec.copy()
            mi, strand = str(r.get_tag("MI")).split("/")
            if mi == fam_mis[1]:
                # same MI as family 0, >flush-margin away: refragmentation
                r.pos = r.pos % 5_000 + 20_000
                if r.next_pos >= 0:
                    r.next_pos = r.next_pos % 5_000 + 20_000
            else:
                r.pos = r.pos % 5_000
                if r.next_pos >= 0:
                    r.next_pos = r.next_pos % 5_000
            r.set_tag("MI", f"9/{strand}", "Z")
            shifted.append(r)
        shifted.sort(key=lambda r: r.pos)

        def fetch(_n, start, end):
            return genome[start:end]

        stats = StageStats()
        duplex = list(call_duplex(
            iter(shifted), fetch, [name], mode="self",
            grouping="coordinate", stats=stats,
        ))
        assert stats.refragmented_families == 1
        # both fragments emit, and each carries raw (not zeroed/presence)
        # strand depths
        lows = [r for r in duplex if r.pos < 10_000]
        highs = [r for r in duplex if r.pos >= 10_000]
        assert lows and highs
        for rec in duplex:
            _sub, ad = rec.get_tag("ad")
            assert max(ad) >= 3, (rec.pos, list(ad))

    def test_native_rawize_matches_python_fallback(self, monkeypatch):
        """The C rawize pass (io.wirepack.duplex_rawize) and the numpy
        fallback loop must produce identical raw tag surfaces."""
        from bsseqconsensusreads_tpu.io import wirepack

        if not wirepack.available():
            pytest.skip("native wirepack not built")
        _, with_native = self._chain(seed=99)
        monkeypatch.setattr(wirepack, "available", lambda: False)
        _, without = self._chain(seed=99)

        def surface(recs):
            return sorted(
                (
                    r.qname, r.flag, r.pos, r.seq,
                    tuple(r.get_tag("cd")[1]), tuple(r.get_tag("ce")[1]),
                    tuple(r.get_tag("ad")[1]), tuple(r.get_tag("bd")[1]),
                    int(r.get_tag("aD")), int(r.get_tag("bD")),
                )
                for r in recs
            )

        assert surface(with_native) == surface(without)
