"""End-to-end multi-contig pipeline behavior.

Real genomes carry dozens of contigs; the reference pipeline handles them
via samtools/fgbio coordinate semantics. This exercises the framework's
full self-aligned pipeline over a 3-contig reference — families on every
contig including spans ending at a contig boundary — and checks contig
attribution, cross-contig coordinate ordering, consensus content, and
engine parity (native vs python ingest+emit byte-identical).
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from bsseqconsensusreads_tpu.config import FrameworkConfig
from bsseqconsensusreads_tpu.io.bam import BamHeader, BamReader, BamRecord, BamWriter, CMATCH
from bsseqconsensusreads_tpu.pipeline.stages import run_pipeline
from bsseqconsensusreads_tpu.utils.testing import bisulfite_convert, random_genome


READ = 40


def _fasta_multi(path: str, contigs: dict[str, str]) -> None:
    with open(path, "w") as fh:
        for name, seq in contigs.items():
            fh.write(f">{name}\n")
            for i in range(0, len(seq), 60):
                fh.write(seq[i : i + 60] + "\n")


@pytest.fixture(scope="module")
def multicontig(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("mc")
    rng = np.random.default_rng(41)
    contigs = {
        "chrA": random_genome(rng, 900, "chrA")[1],
        "chrB": random_genome(rng, 500, "chrB")[1],
        "chrC": random_genome(rng, 700, "chrC")[1],
    }
    fasta = str(tmp / "genome.fa")
    _fasta_multi(fasta, contigs)
    header = BamHeader(
        "@HD\tVN:1.6\tSO:coordinate\n",
        [(n, len(s)) for n, s in contigs.items()],
    )
    names = list(contigs)
    records = []
    mi = 0
    placements = []  # (ref_id, start)
    for ref_id, name in enumerate(names):
        L = len(contigs[name])
        starts = [30, L // 2, L - 2 * READ - 1]  # last family touches the end
        for s in starts:
            placements.append((ref_id, s))
    # interleave input records in coordinate order per contig
    for ref_id, start in placements:
        name = names[ref_id]
        genome = contigs[name]
        frag_r2 = start + READ
        for strand, (lf, rf) in (("A", (99, 147)), ("B", (163, 83))):
            for flag, pos in ((lf, start), (rf, frag_r2)):
                seq = bisulfite_convert(
                    genome[pos : pos + READ], genome, pos, strand
                )
                r = BamRecord(
                    qname=f"m{mi}:{strand}", flag=flag, ref_id=ref_id,
                    pos=pos, mapq=60, cigar=[(CMATCH, READ)],
                    next_ref_id=ref_id,
                    next_pos=frag_r2 if flag == lf else start,
                    seq=seq, qual=bytes([35] * READ),
                )
                r.set_tag("MI", f"{mi}/{strand}", "Z")
                r.set_tag("RX", "AC-GT", "Z")
                records.append(r)
        mi += 1
    inp = str(tmp / "in.bam")
    with BamWriter(inp, header) as w:
        w.write_all(records)
    return tmp, fasta, inp, contigs, names, placements


def _run(tmp, fasta, inp, engines: str):
    cfg = FrameworkConfig(
        genome_dir=os.path.dirname(fasta),
        genome_fasta_file_name=os.path.basename(fasta),
        tmp=str(tmp),
        aligner="self",
        grouping="coordinate",
        ingest=engines,
        emit=engines,
    )
    outdir = str(tmp / f"out_{engines}")
    target, _, stats = run_pipeline(cfg, inp, outdir=outdir)
    return target, stats


def test_multicontig_end_to_end(multicontig):
    tmp, fasta, inp, contigs, names, placements = multicontig
    target, stats = _run(tmp, fasta, inp, "python")
    recs = list(BamReader(target))
    # one duplex consensus pair per family
    assert len(recs) == 2 * len(placements)
    # cross-contig coordinate order (the external sort key)
    keys = [(r.ref_id, r.pos) for r in recs]
    assert keys == sorted(keys)
    # every contig produced records, attributed correctly, content matches
    seen_refs = set()
    by_family: dict[str, list] = {}
    for r in recs:
        seen_refs.add(r.ref_id)
        by_family.setdefault(r.qname, []).append(r)
    assert seen_refs == {0, 1, 2}
    for fam_recs in by_family.values():
        assert len(fam_recs) == 2
        for r in fam_recs:
            genome = contigs[names[r.ref_id]]
            want = genome[r.pos : r.pos + len(r.seq)]
            # consensus in CT space equals the A-strand representation
            assert r.seq == bisulfite_convert(
                want, genome, r.pos, "A"
            ), (r.qname, r.flag)
    assert stats["duplex"].skipped_families == 0


def test_multicontig_engine_parity(multicontig):
    tmp, fasta, inp, *_ = multicontig
    a, _ = _run(tmp, fasta, inp, "python")
    b, _ = _run(tmp, fasta, inp, "native")
    assert open(a, "rb").read() == open(b, "rb").read()
