"""Consensus filtering (fgbio FilterConsensusReads equivalent,
pipeline.filter).

The reference is unfiltered by design (reference README.md:9) but left a
dead filtered-variant rule behind (main.snake.py:70-80); these tests pin
the framework's supplied replacement: the M/A/B depth triplet at read
and base level, error-rate drops, quality masking, the no-call fraction,
and template-atomic dropping.
"""

import numpy as np
import pytest

from bsseqconsensusreads_tpu.io.bam import BamRecord, CMATCH
from bsseqconsensusreads_tpu.pipeline.calling import call_molecular
from bsseqconsensusreads_tpu.pipeline.filter import (
    FilterParams,
    FilterStats,
    filter_consensus,
)
from bsseqconsensusreads_tpu.utils.testing import (
    make_grouped_bam_records,
    random_genome,
)


def consensus_rec(
    qname="c1",
    flag=0,
    seq="ACGTACGT",
    qual=None,
    cd=None,
    ce=None,
    cE=0.0,
    ad=None,
    bd=None,
):
    n = len(seq)
    rec = BamRecord(
        qname=qname, flag=flag, ref_id=0, pos=10, mapq=60,
        cigar=[(CMATCH, n)], seq=seq,
        qual=bytes(qual) if qual is not None else bytes([30] * n),
    )
    cd = cd if cd is not None else [4] * n
    rec.set_tag("cd", ("S", list(cd)), "B")
    rec.set_tag("ce", ("S", list(ce if ce is not None else [0] * n)), "B")
    rec.set_tag("cD", max(cd), "i")
    rec.set_tag("cE", float(cE), "f")
    if ad is not None:
        rec.set_tag("ad", ("S", list(ad)), "B")
        rec.set_tag("bd", ("S", list(bd)), "B")
        rec.set_tag("aD", max(ad), "i")
        rec.set_tag("bD", max(bd), "i")
    return rec


def run(params, *recs):
    stats = FilterStats()
    out = list(filter_consensus(list(recs), params, stats=stats))
    return out, stats


class TestReadLevel:
    def test_depth_drop_molecular(self):
        out, stats = run(
            FilterParams(min_reads=(5,)), consensus_rec(cd=[4] * 8)
        )
        assert out == [] and stats.dropped_depth == 1
        out, _ = run(FilterParams(min_reads=(4,)), consensus_rec(cd=[4] * 8))
        assert len(out) == 1

    def test_depth_triplet_duplex(self):
        # total 6, strands 4/2: passes (6,3,2) but not (6,3,3)
        rec = lambda: consensus_rec(cd=[6] * 8, ad=[4] * 8, bd=[2] * 8)
        out, _ = run(FilterParams(min_reads=(6, 3, 2)), rec())
        assert len(out) == 1
        out, stats = run(FilterParams(min_reads=(6, 3, 3)), rec())
        assert out == [] and stats.dropped_depth == 1

    def test_error_rate_drop(self):
        out, stats = run(FilterParams(), consensus_rec(cE=0.03))
        assert out == [] and stats.dropped_error_rate == 1
        out, _ = run(FilterParams(max_read_error_rate=0.05), consensus_rec(cE=0.03))
        assert len(out) == 1

    def test_mean_quality_drop(self):
        rec = consensus_rec(qual=[10] * 8)
        out, stats = run(FilterParams(min_mean_base_quality=20.0), rec)
        assert out == [] and stats.dropped_mean_quality == 1

    def test_template_atomic_drop(self):
        r1 = consensus_rec(qname="t", flag=99)
        r2 = consensus_rec(qname="t", flag=147, cE=0.5)  # only R2 fails
        out, stats = run(FilterParams(), r1, r2)
        assert out == []
        assert stats.dropped_error_rate == 1 and stats.dropped_records == 2
        assert stats.records_in == stats.kept_records + stats.dropped_records


class TestBaseLevel:
    def test_low_depth_base_masked(self):
        cd = [4, 4, 1, 4, 4, 4, 4, 4]
        out, stats = run(
            FilterParams(min_reads=(2,), max_no_call_fraction=0.5),
            consensus_rec(cd=cd),
        )
        assert out[0].seq[2] == "N" and out[0].qual[2] == 2
        assert out[0].seq[0] == "A"
        assert stats.masked_bases == 1

    def test_high_error_base_masked(self):
        ce = [0, 0, 0, 2, 0, 0, 0, 0]  # 2/4 = 0.5 > 0.1
        out, _ = run(
            FilterParams(max_no_call_fraction=0.5), consensus_rec(ce=ce)
        )
        assert out[0].seq[3] == "N"

    def test_low_quality_base_masked(self):
        qual = [30] * 8
        qual[5] = 0
        out, _ = run(
            FilterParams(min_base_quality=2, max_no_call_fraction=0.5),
            consensus_rec(qual=qual),
        )
        assert out[0].seq[5] == "N" and out[0].qual[5] == 2

    def test_duplex_strand_floor_masks_bases(self):
        ad = [3, 3, 0, 3, 3, 3, 3, 3]
        bd = [3] * 8
        out, _ = run(
            FilterParams(min_reads=(3, 2, 1), max_no_call_fraction=0.5),
            consensus_rec(cd=[6] * 8, ad=ad, bd=bd),
        )
        assert out[0].seq[2] == "N"  # min-strand depth 0 < B=1

    def test_no_call_fraction_drop(self):
        cd = [1] * 6 + [4, 4]  # 6/8 masked at min_reads 2
        out, stats = run(
            FilterParams(min_reads=(2,), max_no_call_fraction=0.5),
            consensus_rec(cd=cd),
        )
        assert out == [] and stats.dropped_no_call == 1

    def test_existing_n_counts_toward_no_call(self):
        out, stats = run(
            FilterParams(max_no_call_fraction=0.4),
            consensus_rec(seq="NNNNACGT"),
        )
        assert out == [] and stats.dropped_no_call == 1

    def test_clean_read_unchanged(self):
        rec = consensus_rec()
        out, stats = run(FilterParams(), rec)
        assert out[0].seq == rec.seq and out[0].qual == rec.qual
        assert stats.masked_bases == 0 and stats.kept_records == 1


class TestParamsValidation:
    def test_triplet_order_enforced(self):
        with pytest.raises(ValueError, match="non-increasing"):
            FilterParams(min_reads=(1, 2, 3))
        with pytest.raises(ValueError, match="1-3 values"):
            FilterParams(min_reads=(1, 1, 1, 1))

    def test_single_strand_agreement_accepted(self):
        # r5: -s is supported via the duplex emitters' ac/bc strand-call
        # tags (behavior pinned in tests/test_exact_ce.py)
        p = FilterParams(require_single_strand_agreement=True)
        assert p.require_single_strand_agreement

    def test_missing_cd_raises(self):
        rec = BamRecord(qname="x", flag=0, seq="ACGT", qual=b"\x1e" * 4,
                        cigar=[(CMATCH, 4)])
        with pytest.raises(ValueError, match="cd per-base depth"):
            list(filter_consensus([rec], FilterParams()))


def test_filters_real_consensus_output():
    """End-to-end: molecular consensus output (the real tag surface from
    pipeline.calling) through the filter; min_reads above the simulated
    depth range drops everything, 1 keeps everything.

    Locally seeded rng (NOT the session fixture): the "defaults bite"
    assertion below depends on the drawn depths, and drawing from the
    shared session stream would couple it to test-file ordering (the
    documented rng-coupling flake class).  With this seed the draw
    contains both depth-1 strands (always dropped by min_reads=2) and
    deeper strands that survive; re-seeding requires re-checking that
    both sides of the split still occur."""
    local_rng = np.random.default_rng(20260731)
    name, genome = random_genome(local_rng, 4000)
    header, records = make_grouped_bam_records(
        local_rng, name, genome, n_families=6, reads_per_strand=(1, 3)
    )
    consensus = list(call_molecular(records))
    assert consensus
    permissive = FilterParams(
        min_reads=(1,), max_read_error_rate=1.0, max_base_error_rate=1.0,
        min_base_quality=0, max_no_call_fraction=1.0,
    )
    kept, _ = run(permissive, *consensus)
    assert len(kept) == len(consensus)
    # defaults do bite on low-depth noisy families: whatever survives is
    # a subset, and drops are template-atomic (even record count)
    some, stats = run(FilterParams(min_reads=(2,)), *consensus)
    assert len(some) < len(consensus) and len(some) % 2 == 0
    none, stats = run(FilterParams(min_reads=(50,)), *consensus)
    assert none == [] and stats.dropped_depth == stats.templates


def test_duplex_strand_thresholds_assigned_per_read():
    """fgbio assigns the A floor to the deeper strand PER READ and tests
    each strand's own per-base array — element-wise max/min across
    strands would let alternating low-depth positions slip through."""
    ad = [3, 1, 3, 1, 3, 1, 3, 1]
    bd = [1, 3, 1, 3, 1, 3, 1, 3]
    out, stats = run(
        FilterParams(min_reads=(3, 3, 1), max_no_call_fraction=1.0),
        consensus_rec(cd=[4] * 8, ad=ad, bd=bd),
    )
    # ad (deeper by tie->first) carries the A=3 floor: positions where it
    # dips to 1 must mask
    assert out[0].seq.count("N") == 4
    # deeper-strand assignment: swapping the arrays gives the same result
    out2, _ = run(
        FilterParams(min_reads=(3, 3, 1), max_no_call_fraction=1.0),
        consensus_rec(cd=[4] * 8, ad=bd, bd=ad),
    )
    assert out2[0].seq.count("N") == 4
