"""graftpreempt tests: voluntary drain-and-handoff, bounded handoff
latency, and typed overload shedding.

* latch — the SIGTERM latch is sticky (salvo dedup), carries the grace
  budget deadline, and the checkpoint batch gate raises PreemptedError
  with a *durable* batches_kept;
* flush-first — the batch the latch interrupts is flushed durable
  BEFORE the error unwinds (vs. the crash path, which loses the
  partial shard);
* ledger preempt — a valid preempt releases the lease and requeues the
  slice IMMEDIATELY (no lease_s wait), with fencing keeping precedence
  over the lease bookkeeping (a stale-epoch preempt is refused
  `fenced` exactly like a stale publish);
* handoff byte-identity — work_loop preempted mid-slice over real tcp
  (shared-rundir and ship mode) hands off, a successor resumes the
  durable prefix, and the merge equals the single-process SHA, with
  `handoff_latency_s` bounded well below the lease;
* overload — the admission watermark sheds with a typed `overloaded`
  refusal + retry hint (counter and ledger event reconcile), the
  router's forward path backs off and converges, and the wire carries
  the refusal type end-to-end;
* drain deadlines — `drain` budgets are accounted from frame-SEND time
  (`sent_s`), refusing typed (`drain_timeout`) on lapse instead of
  answering an ambiguous ok;
* supervisor — `cli elastic run` SIGTERM drains and reaps every worker
  child (no orphans) and leaves a resumable ledger (slow, subprocess).
"""

import dataclasses
import hashlib
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from bsseqconsensusreads_tpu.config import FrameworkConfig
from bsseqconsensusreads_tpu.elastic import (
    Coordinator,
    SliceLedger,
    config_doc,
    fencing,
    merge as merge_mod,
    slice_name,
    split_input,
    worker as worker_mod,
)
from bsseqconsensusreads_tpu.elastic import preempt as preempt_mod
from bsseqconsensusreads_tpu.faults import failpoints, integrity
from bsseqconsensusreads_tpu.io.bam import BamHeader, BamWriter
from bsseqconsensusreads_tpu.pipeline import checkpoint as ckpt_mod
from bsseqconsensusreads_tpu.pipeline.calling import (
    call_molecular_batches,
)
from bsseqconsensusreads_tpu.pipeline.checkpoint import BatchCheckpoint
from bsseqconsensusreads_tpu.serve import jobs as jobs_mod
from bsseqconsensusreads_tpu.serve import router as router_mod
from bsseqconsensusreads_tpu.serve import transport
from bsseqconsensusreads_tpu.serve.jobs import JobQueue, JobSpec
from bsseqconsensusreads_tpu.serve.router import Router
from bsseqconsensusreads_tpu.serve.server import ProtocolServer
from bsseqconsensusreads_tpu.utils.testing import (
    make_grouped_bam_records,
    random_genome,
    write_fasta,
)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


@pytest.fixture(autouse=True)
def _clean_slate():
    """The latch, the batch gate, the fence, and the SIGTERM handler
    are process-global: every test leaves them as it found them."""
    yield
    preempt_mod.FLAG.clear()
    ckpt_mod.install_batch_gate(None)
    fencing.release()
    failpoints.disarm()
    # in-process work_loop sets the elastic identity env (worker.py);
    # left behind, observe.emit would stamp THAT worker id over every
    # later test's payloads
    os.environ.pop("BSSEQ_TPU_WORKER_ID", None)
    os.environ.pop("BSSEQ_TPU_COORDINATOR_ADDR", None)
    try:
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
    except ValueError:
        pass


def _events(path):
    out = []
    if os.path.exists(path):
        with open(path) as fh:
            for line in fh:
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    return out


def _sha(path):
    with open(path, "rb") as fh:
        return hashlib.sha256(fh.read()).hexdigest()


# ---------------------------------------------------------------------------
# latch + grace budget + batch gate


class TestLatch:
    def test_latch_is_sticky_and_dedups_salvos(self):
        flag = preempt_mod.PreemptFlag()
        assert not flag.pending()
        assert flag.requested_at() == 0.0
        assert flag.request() is True
        assert flag.pending()
        t0 = flag.requested_at()
        assert t0 > 0.0
        # the grid sends SIGTERM in salvos: the second must not
        # restart the latency clock
        assert flag.request() is False
        assert flag.requested_at() == t0
        flag.clear()
        assert not flag.pending()
        assert flag.requested_at() == 0.0

    def test_grace_env(self, monkeypatch):
        monkeypatch.delenv(preempt_mod.ENV_GRACE_S, raising=False)
        assert preempt_mod.grace_s() == preempt_mod.DEFAULT_GRACE_S
        monkeypatch.setenv(preempt_mod.ENV_GRACE_S, "12.5")
        assert preempt_mod.grace_s() == 12.5
        monkeypatch.setenv(preempt_mod.ENV_GRACE_S, "not-a-float")
        assert preempt_mod.grace_s() == preempt_mod.DEFAULT_GRACE_S

    def test_deadline_tracks_grace_budget(self, monkeypatch):
        monkeypatch.setenv(preempt_mod.ENV_GRACE_S, "5")
        flag = preempt_mod.PreemptFlag()
        flag.request()
        assert abs(flag.deadline() - (flag.requested_at() + 5.0)) < 0.01

    def test_sigterm_latches_instead_of_killing(self):
        flag = preempt_mod.PreemptFlag()
        assert preempt_mod.install_signal_handler(flag)
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 5.0
        while not flag.pending() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert flag.pending()

    def test_install_off_main_thread_is_refused_not_fatal(self):
        box = {}
        t = threading.Thread(
            target=lambda: box.update(
                ok=preempt_mod.install_signal_handler()
            )
        )
        t.start()
        t.join(timeout=10.0)
        assert box["ok"] is False

    def test_batch_gate_raises_with_durable_count(self):
        flag = preempt_mod.PreemptFlag()
        gate = preempt_mod.batch_gate(flag)
        gate(3)  # unlatched: a no-op
        flag.request()
        with pytest.raises(preempt_mod.PreemptedError) as ei:
            gate(3)
        assert ei.value.batches_kept == 3


class TestHandoffManifest:
    def test_roundtrip_is_atomic(self, tmp_path):
        sdir = str(tmp_path / "slice_0000")
        path = preempt_mod.write_handoff(
            sdir, slice_name="slice_0000", worker="w0", batches_kept=7
        )
        assert os.path.basename(path) == preempt_mod.HANDOFF_NAME
        assert not os.path.exists(path + ".tmp")
        manifest = preempt_mod.read_handoff(sdir)
        assert manifest["batches_kept"] == 7
        # the durable batch count IS the methyl watermark (tallies
        # flush inside on_flush before the manifest advances)
        assert manifest["methyl_watermark"] == manifest["batches_kept"]
        assert manifest["worker"] == "w0"

    def test_read_absent_is_none(self, tmp_path):
        assert preempt_mod.read_handoff(str(tmp_path)) is None


# ---------------------------------------------------------------------------
# flush-first: the interrupting batch is durable before control unwinds


class TestBatchGateFlushFirst:
    def test_pending_batch_flushed_before_preempt_unwinds(self, tmp_path):
        rng = np.random.default_rng(77)
        gname, genome = random_genome(rng, 3000)
        header, records = make_grouped_bam_records(
            rng, gname, genome, n_families=40
        )
        uh = BamHeader(
            text="@HD\tVN:1.6\tSO:unsorted\n",
            references=header.references,
        )
        target = str(tmp_path / "consensus.bam")
        ck = BatchCheckpoint(target, uh, every=2)
        flag = preempt_mod.PreemptFlag()
        latched_at = {"batch": None}

        real_gate = preempt_mod.batch_gate(flag)

        def gate(batches_done):
            if batches_done == 3:
                flag.request()
                latched_at["batch"] = batches_done
            real_gate(batches_done)

        ckpt_mod.install_batch_gate(gate)
        with pytest.raises(preempt_mod.PreemptedError) as ei:
            ck.write_batches(
                call_molecular_batches(iter(records), batch_families=8)
            )
        # the crash path (test_checkpoint) keeps only FULL shards: an
        # interrupt at batch 3 with every=2 would leave 2 durable. The
        # preempt gate flushes the pending buffer first, so batch 3 —
        # the batch the latch interrupted — is on disk too.
        assert latched_at["batch"] == 3
        assert ei.value.batches_kept == 3
        assert ck.batches_done == 3
        manifest = json.loads(
            (tmp_path / "consensus.bam.ckpt.json").read_text()
        )
        assert manifest["batches_done"] == 3


# ---------------------------------------------------------------------------
# ledger: immediate requeue + fencing precedence


def _fake_rundir(tmp_path, n=2):
    rundir = str(tmp_path / "run")
    specs = []
    for sid in range(n):
        os.makedirs(
            os.path.join(rundir, "slices", slice_name(sid)), exist_ok=True
        )
        specs.append({
            "sid": sid,
            "path": os.path.join("slices", f"{slice_name(sid)}.bam"),
            "records": 5 + sid,
            "families": 2,
            "family_crc": 1000 + sid,
            "input_crc": 0,
        })
    return rundir, specs


def _out(rundir, sid, payload=b"consensus-bytes"):
    path = os.path.join(rundir, "slices", slice_name(sid), "out.bam")
    with open(path, "wb") as fh:
        fh.write(payload)
    return {
        "slice": slice_name(sid),
        "output": "out.bam",
        "crc": integrity.file_crc32(path),
        "family_crc": 1000 + sid,
        "records_out": 2,
    }


class TestLedgerPreempt:
    def test_preempt_requeues_immediately_no_lease_wait(
        self, tmp_path, monkeypatch
    ):
        sink = str(tmp_path / "ledger.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        rundir, specs = _fake_rundir(tmp_path, n=1)
        # lease_s is an HOUR: only a voluntary release can requeue
        # inside this test's lifetime
        ledger = SliceLedger(rundir, specs, lease_s=3600.0)
        grant = ledger.lease("w0")
        t0 = time.monotonic()
        resp = ledger.preempt(
            "w0", grant["lease_id"], 0, batches_kept=2,
            epoch=grant.get("fence_epoch"),
        )
        assert resp == {"ok": True}
        regrant = ledger.lease("w1")
        assert time.monotonic() - t0 < 5.0  # nothing waited for expiry
        assert regrant["slice"]["sid"] == 0
        assert regrant["lease_id"] != grant["lease_id"]
        # the successor's fence epoch supersedes the departed holder's
        assert regrant["fence_epoch"] > grant["fence_epoch"]
        counts = ledger.counts()
        assert counts["preempts"] == 1 and counts["requeues"] == 1
        events = _events(sink)
        pre = [e for e in events if e.get("event") == "worker_preempted"]
        assert len(pre) == 1
        assert pre[0]["reason"] == "handoff"
        assert pre[0]["batches_kept"] == 2
        req = [e for e in events if e.get("event") == "slice_requeued"]
        assert len(req) == 1 and req[0]["reason"] == "preempted"
        # the old holder's lease is gone: its heartbeat is refused
        assert not ledger.heartbeat("w0", grant["lease_id"])

    def test_preempt_unknown_lease_refused(self, tmp_path):
        rundir, specs = _fake_rundir(tmp_path, n=1)
        ledger = SliceLedger(rundir, specs, lease_s=3600.0)
        ledger.lease("w0")
        resp = ledger.preempt("w0", "no-such-lease", 0)
        assert resp == {"ok": False, "reason": "lease_expired"}
        assert ledger.counts()["preempts"] == 0

    def test_preempt_stale_epoch_fenced_with_precedence(
        self, tmp_path, monkeypatch
    ):
        """PR 18 precedence holds for the preempt op too: a preempt
        carrying an epoch below the slice's current grant is a zombie
        and is refused `fenced` BEFORE any lease bookkeeping runs —
        it must not release the successor's lease."""
        sink = str(tmp_path / "ledger.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        rundir, specs = _fake_rundir(tmp_path, n=1)
        ledger = SliceLedger(rundir, specs, lease_s=3600.0)
        stale = ledger.lease("w0")
        ledger.note_worker_dead("w0")  # requeue: w0 is now a zombie
        fresh = ledger.lease("w1")
        assert fresh["fence_epoch"] > stale["fence_epoch"]
        resp = ledger.preempt(
            "w0", stale["lease_id"], 0, batches_kept=1,
            epoch=stale["fence_epoch"],
        )
        assert resp["ok"] is False
        assert resp["reason"] == "fenced"
        assert resp["epoch"] == fresh["fence_epoch"]
        assert ledger.counts()["preempts"] == 0
        # the successor's lease survived the zombie's preempt
        assert ledger.heartbeat("w1", fresh["lease_id"])
        fenced = [
            e for e in _events(sink) if e.get("event") == "publish_fenced"
        ]
        assert len(fenced) == 1 and fenced[0]["worker"] == "w0"


# ---------------------------------------------------------------------------
# handoff byte-identity (in-process work_loop over real tcp)


N_FAMILIES = 8


@pytest.fixture(scope="module")
def preempt_env(tmp_path_factory):
    from bsseqconsensusreads_tpu.pipeline.stages import run_pipeline

    tmp = tmp_path_factory.mktemp("preempt")
    rng = np.random.default_rng(2008)
    name, genome = random_genome(rng, 5000)
    fasta = str(tmp / "genome.fa")
    write_fasta(fasta, name, genome)
    header, records = make_grouped_bam_records(
        rng, name, genome, n_families=N_FAMILIES, error_rate=0.01
    )
    bam = str(tmp / "preempt.bam")
    with BamWriter(bam, header) as w:
        w.write_all(records)
    cfg = FrameworkConfig(
        genome_dir=os.path.dirname(fasta),
        genome_fasta_file_name=os.path.basename(fasta),
        aligner="self",
        # small batches: a slice spans several batches, so the gate
        # really interrupts MID-slice with a durable prefix behind it
        batch_families=2,
    )
    sp_cfg = dataclasses.replace(cfg, tmp=str(tmp / "sp_tmp"))
    target, _results, _stats = run_pipeline(
        sp_cfg, bam, outdir=str(tmp / "single")
    )
    return {"bam": bam, "cfg": cfg, "sp_sha": _sha(target)}


class TestHandoffByteIdentity:
    @pytest.mark.parametrize("ship", [False, True])
    def test_preempted_worker_hands_off_successor_matches_sha(
        self, preempt_env, tmp_path, monkeypatch, ship
    ):
        """SIGTERM mid-slice (the latch set between batches): the
        worker flushes, writes the handoff manifest (shared-rundir
        mode), releases its lease via the preempt op, and exits 0; the
        coordinator requeues immediately; a successor resumes the
        durable prefix and the merge equals the single-process SHA."""
        sink = str(tmp_path / "ledger.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        outdir = str(tmp_path / "out")
        rundir = os.path.join(outdir, "elastic")
        os.makedirs(rundir, exist_ok=True)
        cfg = preempt_env["cfg"]
        specs = split_input(preempt_env["bam"], rundir, 2)
        lease_s = 300.0
        ledger = SliceLedger(rundir, specs, lease_s=lease_s)
        server = Coordinator(
            ledger, config_doc(cfg), addresses=["tcp:127.0.0.1:0"],
            ship=ship,
        )
        server.start_monitor()
        # graftlint: owned-thread -- test coordinator accept loop,
        # drained below
        thread = threading.Thread(
            target=server.serve_forever, daemon=True
        )
        thread.start()

        # stand-in for SIGTERM: latch once the 2nd batch of the first
        # leased slice is in flight (the handler does exactly this)
        arm = {"on": True}
        real_gate_factory = preempt_mod.batch_gate

        def triggering_gate_factory(flag=None):
            real = real_gate_factory(flag)

            def gate(batches_done):
                if arm["on"] and batches_done >= 2:
                    preempt_mod.FLAG.request()
                real(batches_done)

            return gate

        monkeypatch.setattr(
            preempt_mod, "batch_gate", triggering_gate_factory
        )
        try:
            deadline = time.monotonic() + 10.0
            while not server.bound and time.monotonic() < deadline:
                time.sleep(0.01)
            assert server.bound
            done0 = worker_mod.work_loop(
                server.bound[0], worker_id="pw0"
            )
            assert done0 == 0  # preempted before publishing anything
            counts = ledger.counts()
            assert counts["preempts"] == 1
            assert counts["requeues"] == 1
            handoff = preempt_mod.read_handoff(
                os.path.join(rundir, "slices", slice_name(0))
            )
            if ship:
                # shared-nothing: the private workdir is gone with the
                # worker; successors refetch, nothing lands in rundir
                assert handoff is None
            else:
                assert handoff["batches_kept"] >= 2
                assert handoff["worker"] == "pw0"
            # successor: same protocol, no latch
            arm["on"] = False
            preempt_mod.FLAG.clear()
            done1 = worker_mod.work_loop(
                server.bound[0], worker_id="pw1"
            )
            assert done1 == 2  # the requeued slice + the untouched one
        finally:
            server.request_drain()
            thread.join(timeout=10.0)
        target, report = merge_mod.finalize(
            cfg, preempt_env["bam"], outdir, specs, ledger.manifests()
        )
        assert report["ok"], report["checks"]
        assert _sha(target) == preempt_env["sp_sha"]
        events = _events(sink)
        published = [
            e for e in events if e.get("event") == "handoff_published"
        ]
        assert len(published) == 1
        assert published[0]["worker"] == "pw0"
        assert published[0]["batches_kept"] >= 2
        # THE bound: voluntary handoff must beat lease-expiry recovery
        # by an order of magnitude — latency is one batch + one rpc
        latency = published[0]["handoff_latency_s"]
        assert 0.0 <= latency < 30.0 < lease_s
        preempted = [
            e for e in events if e.get("event") == "worker_preempted"
        ]
        assert len(preempted) == 1
        assert preempted[0]["worker"] == "pw0"
        assert preempted[0]["reason"] == "handoff"


# ---------------------------------------------------------------------------
# overload shedding: watermark, typed refusal, bounded backoff


GENOME = "".join(
    "ACGT"[i] for i in np.random.default_rng(7).integers(0, 4, size=2000)
)


def _grouped_bam(path, seed, n_families=4):
    header, records = make_grouped_bam_records(
        np.random.default_rng(seed), f"chr{seed % 97}", GENOME,
        n_families=n_families, reads_per_strand=(2, 3), read_len=40,
    )
    with BamWriter(path, header) as w:
        for r in records:
            w.write(r)


class TestAdmitWatermark:
    def test_default_passthrough(self, monkeypatch):
        monkeypatch.delenv(jobs_mod.ENV_ADMIT_WATERMARK, raising=False)
        assert jobs_mod.admit_watermark(64) == 64
        assert jobs_mod.admit_watermark(0) == 0

    def test_env_overrides(self, monkeypatch):
        monkeypatch.setenv(jobs_mod.ENV_ADMIT_WATERMARK, "3")
        assert jobs_mod.admit_watermark(64) == 3

    def test_bad_env_falls_back(self, monkeypatch):
        monkeypatch.setenv(jobs_mod.ENV_ADMIT_WATERMARK, "three")
        assert jobs_mod.admit_watermark(64) == 64
        monkeypatch.setenv(jobs_mod.ENV_ADMIT_WATERMARK, "-5")
        assert jobs_mod.admit_watermark(64) == 0  # clamped: disabled


class TestQueueShedding:
    def _spec(self, tmp_path, k):
        inp = str(tmp_path / f"in{k}.bam")
        if not os.path.exists(inp):
            _grouped_bam(inp, seed=k + 1)
        return JobSpec.from_dict(
            {"input": inp, "output": inp + ".out"}
        )

    def test_sheds_at_watermark_with_reconciled_counter(
        self, tmp_path, monkeypatch
    ):
        sink = str(tmp_path / "ledger.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        monkeypatch.delenv(jobs_mod.ENV_ADMIT_WATERMARK, raising=False)
        q = JobQueue(max_pending=2)  # watermark defaults to capacity
        q.submit(self._spec(tmp_path, 0))
        q.submit(self._spec(tmp_path, 1))
        with pytest.raises(jobs_mod.OverloadedError) as ei:
            q.submit(self._spec(tmp_path, 2))
        assert 0.05 <= ei.value.retry_after_s <= 5.0
        assert q.counters["jobs_shed"] == 1
        shed = [
            e for e in _events(sink) if e.get("event") == "jobs_shed"
        ]
        # counter and ledger evidence must reconcile 1:1
        assert len(shed) == q.counters["jobs_shed"] == 1
        assert shed[0]["depth"] == 2 and shed[0]["watermark"] == 2
        assert shed[0]["retry_after_s"] == ei.value.retry_after_s

    def test_env_watermark_sheds_below_capacity(
        self, tmp_path, monkeypatch
    ):
        monkeypatch.setenv(jobs_mod.ENV_ADMIT_WATERMARK, "1")
        q = JobQueue(max_pending=64)
        q.submit(self._spec(tmp_path, 0))
        with pytest.raises(jobs_mod.OverloadedError):
            q.submit(self._spec(tmp_path, 1))
        assert q.counters["jobs_shed"] == 1

    def test_shed_is_not_terminal_backlog_drains_then_admits(
        self, tmp_path, monkeypatch
    ):
        monkeypatch.setenv(jobs_mod.ENV_ADMIT_WATERMARK, "1")
        q = JobQueue(max_pending=64)
        q.submit(self._spec(tmp_path, 0))
        with pytest.raises(jobs_mod.OverloadedError):
            q.submit(self._spec(tmp_path, 1))
        # overload is a state, not a verdict: once the backlog drains
        # the same submit is admitted
        assert q.claim() is not None
        job = q.submit(self._spec(tmp_path, 1))
        assert job.id


class _FakeReplica:
    def __init__(self, rid):
        self.rid = rid
        self.address = f"tcp:127.0.0.1:1{rid[1:]}"
        self.proc = None
        self.generation = 0
        self.up = True

    @property
    def supervised(self):
        return True

    def alive(self):
        return self.up


class _FakeFleet:
    def __init__(self, n=2):
        self.replicas = [_FakeReplica(f"r{i}") for i in range(n)]

    def alive(self):
        return [r for r in self.replicas if r.alive()]

    def lookup(self, rid):
        for r in self.replicas:
            if r.rid == rid:
                return r
        return None

    def restart(self, replica):
        replica.generation += 1
        replica.up = True


class TestRouterShedding:
    def test_router_watermark_sheds_typed(self, monkeypatch, tmp_path):
        calls = {"n": 0}

        def fake_request(address, payload, timeout=0.0):
            if payload.get("op") == "submit":
                calls["n"] += 1
                return {"ok": True,
                        "job": {"id": f"j{calls['n']:04d}",
                                "state": "queued"}}
            return {"ok": True}

        monkeypatch.setattr(
            router_mod._transport, "request", fake_request
        )
        monkeypatch.setenv(jobs_mod.ENV_ADMIT_WATERMARK, "1")
        router = Router(replicas=_FakeFleet(2))  # no launch(): no monitor
        inp = str(tmp_path / "in.bin")
        with open(inp, "wb") as fh:
            fh.write(b"x" * 64)
        assert router.submit({"input": inp, "output": inp + ".o"})["ok"]
        with pytest.raises(transport.TransportError) as ei:
            router.submit({"input": inp, "output": inp + ".o2"})
        assert ei.value.reason == "overloaded"
        assert 0.05 <= ei.value.retry_after_s <= 5.0
        assert router.counters["jobs_shed"] == 1

    def test_router_watermark_disabled_without_env(
        self, monkeypatch, tmp_path
    ):
        def fake_request(address, payload, timeout=0.0):
            if payload.get("op") == "submit":
                return {"ok": True, "job": {"id": "j1", "state": "queued"}}
            return {"ok": True}

        monkeypatch.setattr(
            router_mod._transport, "request", fake_request
        )
        monkeypatch.delenv(jobs_mod.ENV_ADMIT_WATERMARK, raising=False)
        router = Router(replicas=_FakeFleet(1))
        inp = str(tmp_path / "in.bin")
        with open(inp, "wb") as fh:
            fh.write(b"x" * 64)
        for k in range(8):
            assert router.submit(
                {"input": inp, "output": f"{inp}.{k}"}
            )["ok"]
        assert router.counters["jobs_shed"] == 0

    def test_forward_backs_off_on_replica_shed_and_converges(
        self, monkeypatch, tmp_path
    ):
        """A replica answering `overloaded` is not dead: the forward
        path sleeps the replica's own retry hint and retries, so a
        transient storm converges instead of failing the job."""
        attempts = {"n": 0}

        def fake_request(address, payload, timeout=0.0):
            if payload.get("op") == "submit":
                attempts["n"] += 1
                if attempts["n"] <= 2:
                    return {"ok": False, "guard": "overloaded",
                            "error": "refused: shed",
                            "retry_after_s": 0.01}
                return {"ok": True,
                        "job": {"id": "j0001", "state": "queued"}}
            return {"ok": True}

        monkeypatch.setattr(
            router_mod._transport, "request", fake_request
        )
        monkeypatch.delenv(jobs_mod.ENV_ADMIT_WATERMARK, raising=False)
        router = Router(replicas=_FakeFleet(1))
        inp = str(tmp_path / "in.bin")
        with open(inp, "wb") as fh:
            fh.write(b"x" * 64)
        resp = router.submit({"input": inp, "output": inp + ".o"})
        assert resp["ok"]
        assert attempts["n"] == 3  # two sheds, then admitted

    def test_forward_exhaustion_returns_the_typed_shed(
        self, monkeypatch, tmp_path
    ):
        def fake_request(address, payload, timeout=0.0):
            if payload.get("op") == "submit":
                return {"ok": False, "guard": "overloaded",
                        "error": "refused: shed", "retry_after_s": 0.01}
            return {"ok": True}

        monkeypatch.setattr(
            router_mod._transport, "request", fake_request
        )
        monkeypatch.delenv(jobs_mod.ENV_ADMIT_WATERMARK, raising=False)
        router = Router(replicas=_FakeFleet(1), forward_retries=2)
        inp = str(tmp_path / "in.bin")
        with open(inp, "wb") as fh:
            fh.write(b"x" * 64)
        resp = router.submit({"input": inp, "output": inp + ".o"})
        # the caller sees the typed refusal (retry-able), not a
        # fabricated transport error
        assert resp["ok"] is False
        assert resp.get("guard") == "overloaded"


class _Overloaded(ProtocolServer):
    """Server whose dispatch sheds: the typed-refusal path end-to-end."""

    def _dispatch(self, req):
        if req.get("op") == "drain":
            return self._drain_op(req)
        err = transport.TransportError(
            "admission queue at depth 9 >= watermark 8; job shed",
            reason="overloaded",
        )
        err.retry_after_s = 0.25
        raise err

    def _on_drain(self):
        pass


class TestWireRefusal:
    def test_overloaded_refusal_rides_the_wire_typed(
        self, tmp_path, monkeypatch
    ):
        sink = str(tmp_path / "ledger.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        srv = _Overloaded(addresses=["tcp:127.0.0.1:0"])
        # graftlint: owned-thread -- test accept loop, drained below
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            deadline = time.monotonic() + 10.0
            while not srv.bound and time.monotonic() < deadline:
                time.sleep(0.01)
            resp = transport.request(
                srv.bound[0], {"op": "submit", "spec": {}}, timeout=5.0
            )
        finally:
            srv.request_drain()
            t.join(timeout=10.0)
        assert resp["ok"] is False
        assert resp["guard"] == "overloaded"
        assert resp["retry_after_s"] == 0.25
        assert resp["error"].startswith("refused:")
        refused = [
            e for e in _events(sink)
            if e.get("event") == "serve_frame_refused"
        ]
        assert any(e["reason"] == "overloaded" for e in refused)


# ---------------------------------------------------------------------------
# drain deadlines accounted from frame-send time (satellite: the same
# bug class PR 18 fixed in the lease-renewal pump)


class _SlowDrain(ProtocolServer):
    def __init__(self, *a, drain_s=0.0, **k):
        super().__init__(*a, **k)
        self.drain_s = drain_s

    def _dispatch(self, req):
        if req.get("op") == "drain":
            return self._drain_op(req)
        return {"ok": True}

    def _on_drain(self):
        if self.drain_s:
            time.sleep(self.drain_s)


def _serve(srv):
    # graftlint: owned-thread -- test accept loop, drained by the test
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    deadline = time.monotonic() + 10.0
    while not srv.bound and time.monotonic() < deadline:
        time.sleep(0.01)
    assert srv.bound
    return t


class TestDrainDeadline:
    def test_budget_counts_from_send_not_receipt(self):
        """A drain frame that spent its whole budget in flight (or in
        the accept queue) is ALREADY late: the server must refuse
        typed, not grant itself a fresh budget at receipt."""
        srv = _SlowDrain(addresses=["tcp:127.0.0.1:0"], drain_s=0.0)
        t = _serve(srv)
        try:
            resp = transport.request(
                srv.bound[0],
                {"op": "drain", "timeout": 5.0,
                 "sent_s": time.time() - 60.0},
                timeout=10.0,
            )
        finally:
            srv.request_drain()
            t.join(timeout=10.0)
        assert resp["ok"] is False
        assert resp["guard"] == "drain_timeout"

    def test_drain_within_budget_completes_ok(self):
        srv = _SlowDrain(addresses=["tcp:127.0.0.1:0"], drain_s=0.2)
        t = _serve(srv)
        try:
            resp = transport.request(
                srv.bound[0],
                {"op": "drain", "timeout": 30.0, "sent_s": time.time()},
                timeout=60.0,
            )
        finally:
            t.join(timeout=10.0)
        assert resp == {"ok": True, "drained": True}

    def test_drain_without_sent_s_keeps_receipt_accounting(self):
        srv = _SlowDrain(addresses=["tcp:127.0.0.1:0"], drain_s=0.2)
        t = _serve(srv)
        try:
            resp = transport.request(
                srv.bound[0], {"op": "drain", "timeout": 30.0},
                timeout=60.0,
            )
        finally:
            t.join(timeout=10.0)
        assert resp == {"ok": True, "drained": True}


# ---------------------------------------------------------------------------
# replica voluntary drain: jobs migrate to survivors, no respawn


class TestReplicaDrainMigration:
    def test_preempt_replica_migrates_jobs_no_respawn(
        self, monkeypatch, tmp_path
    ):
        sink = str(tmp_path / "ledger.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        monkeypatch.delenv(jobs_mod.ENV_ADMIT_WATERMARK, raising=False)
        placements = []

        def fake_request(address, payload, timeout=0.0):
            if payload.get("op") == "submit":
                placements.append(address)
                return {"ok": True,
                        "job": {"id": f"j{len(placements):04d}",
                                "state": "queued"}}
            return {"ok": True}

        monkeypatch.setattr(
            router_mod._transport, "request", fake_request
        )
        fleet = _FakeFleet(2)
        router = Router(replicas=fleet)
        inputs = []
        for k in range(4):
            p = str(tmp_path / f"in{k}.bin")
            with open(p, "wb") as fh:
                fh.write(bytes([k]) * 64)
            inputs.append(p)
        for p in inputs:
            assert router.submit({"input": p, "output": p + ".o"})["ok"]
        victim = next(
            j.replica_id for j in router._jobs.values()
        )
        orphaned = [
            j.rid for j in router._jobs.values()
            if j.replica_id == victim
        ]
        assert orphaned
        resp = router.preempt_replica(victim)
        assert resp["ok"]
        assert resp["migrated"] == len(orphaned)
        survivor = next(
            r.rid for r in fleet.replicas if r.rid != victim
        )
        for rid in orphaned:
            job = router._jobs[rid]
            # migrated onto the survivor, never back onto the victim
            assert job.replica_id == survivor
            assert job.state not in ("failed",)
            assert job.requeues == 1
        # the drained replica is OUT: detached from supervision
        # (alive() False via empty address) and never respawned
        replica = fleet.lookup(victim)
        assert replica.address == ""
        assert router.counters["jobs_requeued"] == len(orphaned)
        events = _events(sink)
        pre = [
            e for e in events if e.get("event") == "worker_preempted"
        ]
        assert len(pre) == 1
        assert pre[0]["worker"] == victim
        assert pre[0]["reason"] == "drain"
        req = [e for e in events if e.get("event") == "fleet_requeue"]
        assert len(req) == len(orphaned)
        assert all(e["to_replica"] == survivor for e in req)

    def test_preempt_unknown_replica_refused(self, monkeypatch):
        monkeypatch.setattr(
            router_mod._transport, "request",
            lambda *a, **k: {"ok": True},
        )
        router = Router(replicas=_FakeFleet(1))
        resp = router.preempt_replica("r9")
        assert resp["ok"] is False and "unknown" in resp["error"]

    def test_preempt_dead_replica_refused(self, monkeypatch):
        monkeypatch.setattr(
            router_mod._transport, "request",
            lambda *a, **k: {"ok": True},
        )
        fleet = _FakeFleet(2)
        fleet.replicas[0].up = False
        router = Router(replicas=fleet)
        resp = router.preempt_replica("r0")
        assert resp["ok"] is False and "not alive" in resp["error"]


# ---------------------------------------------------------------------------
# supervisor SIGTERM: drain + reap, no orphans, resumable ledger (slow)


def _children(pid):
    kids = []
    for entry in os.listdir("/proc"):
        if not entry.isdigit():
            continue
        try:
            with open(f"/proc/{entry}/stat") as fh:
                fields = fh.read().split()
        except OSError:
            continue
        if len(fields) > 3 and int(fields[3]) == pid:
            kids.append(int(entry))
    return kids


@pytest.mark.slow
class TestSupervisorSignal:
    def test_sigterm_drains_workers_no_orphans_ledger_resumable(
        self, preempt_env, tmp_path
    ):
        outdir = str(tmp_path / "out")
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            PYTHONPATH=REPO,
            BSSEQ_TPU_STATS=str(tmp_path / "ledger.jsonl"),
            BSSEQ_TPU_PREEMPT_GRACE_S="10",
        )
        env.pop("BSSEQ_TPU_FAILPOINTS", None)
        cfg = preempt_env["cfg"]
        fasta = os.path.join(
            cfg.genome_dir, cfg.genome_fasta_file_name
        )
        args = [
            sys.executable, "-m", "bsseqconsensusreads_tpu.cli",
            "elastic", "run",
            "--bam", preempt_env["bam"],
            "--reference", fasta,
            "--outdir", outdir,
            "--workers", "2", "--slices", "2",
        ]
        proc = subprocess.Popen(
            args, cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        try:
            deadline = time.monotonic() + 120.0
            kids = []
            while time.monotonic() < deadline:
                kids = _children(proc.pid)
                if kids or proc.poll() is not None:
                    break
                time.sleep(0.1)
            assert proc.poll() is None, (
                "run finished before it could be interrupted — "
                "grow the input"
            )
            assert kids, "no worker children appeared"
            proc.send_signal(signal.SIGTERM)
            out, err = proc.communicate(timeout=300)
        except Exception:
            proc.kill()
            proc.communicate()
            raise
        # the supervisor exits loudly (non-zero) with the drain story
        assert proc.returncode != 0
        assert "interrupted" in err
        assert "resumable" in err
        # NO orphans: every worker child is reaped (pid gone, or at
        # worst an exited process someone else owns — never a live
        # python worker of ours)
        for pid in kids:
            assert not os.path.exists(f"/proc/{pid}/stat") or (
                open(f"/proc/{pid}/stat").read().split()[2] in ("Z", "X")
            ), f"worker {pid} survived the supervisor drain"
        # the ledger is terminal + resumable: the SAME command finishes
        # the run from the rundir the drain left behind
        cp = subprocess.run(
            args, cwd=REPO, env=env, capture_output=True, text=True,
            timeout=900,
        )
        assert cp.returncode == 0, cp.stderr
        produced = [
            os.path.join(outdir, f) for f in os.listdir(outdir)
            if f.endswith("_consensus_duplex_unfiltered.bam")
        ]
        assert len(produced) == 1, f"no merged output in {outdir}"
        assert _sha(produced[0]) == preempt_env["sp_sha"]
