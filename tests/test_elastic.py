"""graftswarm (elastic/) tests: split geometry, lease ledger, wire ops,
byte-identity, and loss recovery.

* split — contiguous base-family ordinal ranges: uneven boundaries,
  families never cut across slices, idempotent resume, damaged-slice
  rebuild, ungrouped-input refusal;
* ledger — lease/commit happy path, expiry → requeue, heartbeat
  renewal, lapsed-lease publish refusal + duplicate-commit tolerance,
  fingerprint/integrity refusals, crash-only restart rescan;
* coordinator wire — the elastic op table over the framed transport;
* byte-identity — inline runs over 1/3/4/7 slices and an in-process
  work_loop over real tcp all produce the single-process SHA, and the
  per-slice StageStats sums reconcile against the single-process run;
* loss recovery (slow) — `cli elastic run` fleets (2 and 4 workers),
  a worker killed mid-slice by failpoint (requeue + respawn, same
  bytes), and a TLS coordinator join.

In-process tests stay tier-1; subprocess fleet tests are marked slow,
same split as tests/test_fleet.py.
"""

import dataclasses
import hashlib
import json
import os
import shutil
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from bsseqconsensusreads_tpu.config import FrameworkConfig
from bsseqconsensusreads_tpu.elastic import (
    Coordinator,
    ElasticError,
    SliceLedger,
    base_mi,
    config_doc,
    config_from_doc,
    merge as merge_mod,
    run_elastic,
    slice_name,
    split_input,
    worker as worker_mod,
)
from bsseqconsensusreads_tpu.elastic.coordinator import (
    ENV_COORDINATOR_ADDR,
    ENV_WORKER_ID,
)
from bsseqconsensusreads_tpu.faults import integrity
from bsseqconsensusreads_tpu.io.bam import BamReader, BamWriter
from bsseqconsensusreads_tpu.serve import transport
from bsseqconsensusreads_tpu.utils import ledger_tools
from bsseqconsensusreads_tpu.utils.testing import (
    make_grouped_bam_records,
    random_genome,
    write_fasta,
)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

N_FAMILIES = 10


def _sha(path: str) -> str:
    with open(path, "rb") as fh:
        return hashlib.sha256(fh.read()).hexdigest()


@pytest.fixture(scope="module")
def swarm_env(tmp_path_factory):
    """One grouped input + its single-process pipeline run: the byte
    and counter baseline every elastic test reconciles against."""
    from bsseqconsensusreads_tpu.pipeline.stages import run_pipeline

    tmp = tmp_path_factory.mktemp("swarm")
    rng = np.random.default_rng(905)
    name, genome = random_genome(rng, 6000)
    fasta = str(tmp / "genome.fa")
    write_fasta(fasta, name, genome)
    header, records = make_grouped_bam_records(
        rng, name, genome, n_families=N_FAMILIES, error_rate=0.01
    )
    bam = str(tmp / "input" / "swarm.bam")
    os.makedirs(os.path.dirname(bam), exist_ok=True)
    with BamWriter(bam, header) as w:
        w.write_all(records)
    cfg = FrameworkConfig(
        genome_dir=os.path.dirname(fasta),
        genome_fasta_file_name=os.path.basename(fasta),
        aligner="self",
    )
    sp_out = str(tmp / "single")
    sp_cfg = dataclasses.replace(cfg, tmp=str(tmp / "single_tmp"))
    target, _results, stats = run_pipeline(sp_cfg, bam, outdir=sp_out)
    return {
        "tmp": tmp,
        "fasta": fasta,
        "bam": bam,
        "cfg": cfg,
        "records": len(records),
        "sp_target": target,
        "sp_sha": _sha(target),
        "sp_stats": {stage: s.as_dict() for stage, s in stats.items()},
    }


# ---------------------------------------------------------------------------
# split geometry


class TestSplit:
    def test_uneven_bounds_partition_families(self, swarm_env, tmp_path):
        """10 families over 4 slices: 2/3/2/3, contiguous first-seen
        ordinal ranges, no family cut across slices, no record lost."""
        rundir = str(tmp_path / "run")
        specs = split_input(swarm_env["bam"], rundir, 4)
        assert [sl["families"] for sl in specs] == [2, 3, 2, 3]
        assert sum(sl["records"] for sl in specs) == swarm_env["records"]
        seen_order = []
        with BamReader(swarm_env["bam"]) as r:
            for rec in r:
                fam = base_mi(str(rec.get_tag("MI")))
                if fam not in seen_order:
                    seen_order.append(fam)
        families = []
        for sl in specs:
            fams = []
            with BamReader(os.path.join(rundir, sl["path"])) as r:
                for rec in r:
                    fam = base_mi(str(rec.get_tag("MI")))
                    if fam not in fams:
                        fams.append(fam)
            assert len(fams) == sl["families"]
            families.append(fams)
        flat = [f for fams in families for f in fams]
        # disjoint, complete, and in global first-seen order = contiguous
        assert flat == seen_order

    def test_resume_reuses_intact_slices(self, swarm_env, tmp_path):
        rundir = str(tmp_path / "run")
        specs = split_input(swarm_env["bam"], rundir, 3)
        mtimes = {
            sl["sid"]: os.path.getmtime(os.path.join(rundir, sl["path"]))
            for sl in specs
        }
        again = split_input(swarm_env["bam"], rundir, 3)
        assert again == specs
        for sl in again:
            assert os.path.getmtime(
                os.path.join(rundir, sl["path"])
            ) == mtimes[sl["sid"]]

    def test_damaged_slice_rebuilds(self, swarm_env, tmp_path):
        rundir = str(tmp_path / "run")
        specs = split_input(swarm_env["bam"], rundir, 3)
        victim = os.path.join(rundir, specs[1]["path"])
        with open(victim, "r+b") as fh:
            fh.seek(0)
            fh.write(b"\x00\x00\x00\x00")
        rebuilt = split_input(swarm_env["bam"], rundir, 3)
        assert rebuilt == specs
        integrity.verify_file_crc32(victim, specs[1]["input_crc"])

    def test_slice_count_clamps_to_families(self, swarm_env, tmp_path):
        specs = split_input(swarm_env["bam"], str(tmp_path / "run"), 99)
        assert len(specs) == N_FAMILIES
        assert all(sl["families"] == 1 for sl in specs)

    def test_single_slice_is_whole_input(self, swarm_env, tmp_path):
        (sl,) = split_input(swarm_env["bam"], str(tmp_path / "run"), 1)
        assert sl["records"] == swarm_env["records"]
        assert sl["families"] == N_FAMILIES

    def test_ungrouped_input_refused(self, swarm_env, tmp_path):
        ungrouped = str(tmp_path / "ungrouped.bam")
        with BamReader(swarm_env["bam"]) as r:
            header = r.header
            recs = list(r)
        for rec in recs:
            rec.tags.pop("MI", None)
        with BamWriter(ungrouped, header) as w:
            w.write_all(recs)
        with pytest.raises(ElasticError, match="grouped"):
            split_input(ungrouped, str(tmp_path / "run"), 2)


# ---------------------------------------------------------------------------
# lease ledger (fake slices: no pipeline involved)


def _fake_rundir(tmp_path, n=2):
    """A rundir with n fake slice specs + committed-output scaffolding:
    slice dirs exist, and _out writes a publishable output file."""
    rundir = str(tmp_path / "run")
    specs = []
    for sid in range(n):
        sdir = os.path.join(rundir, "slices", slice_name(sid))
        os.makedirs(sdir, exist_ok=True)
        specs.append({
            "sid": sid,
            "path": os.path.join("slices", f"{slice_name(sid)}.bam"),
            "records": 5 + sid,
            "families": 2,
            "family_crc": 1000 + sid,
            "input_crc": 0,
        })
    return rundir, specs


def _out(rundir, sid, payload=b"consensus-bytes"):
    """Drop a fake slice output and return its publishable manifest."""
    sdir = os.path.join(rundir, "slices", slice_name(sid))
    path = os.path.join(sdir, "out.bam")
    with open(path, "wb") as fh:
        fh.write(payload)
    return {
        "slice": slice_name(sid),
        "output": "out.bam",
        "crc": integrity.file_crc32(path),
        "family_crc": 1000 + sid,
        "records_out": 2,
    }


class TestSliceLedger:
    def test_lease_commit_done(self, tmp_path):
        rundir, specs = _fake_rundir(tmp_path, n=2)
        ledger = SliceLedger(rundir, specs, lease_s=30.0)
        sids = []
        for _ in range(2):
            grant = ledger.lease("w0")
            sid = grant["slice"]["sid"]
            sids.append(sid)
            assert grant["lease_id"].startswith(slice_name(sid))
            resp = ledger.commit(
                grant["lease_id"], sid, _out(rundir, sid), worker="w0"
            )
            assert resp == {"ok": True}
        assert sorted(sids) == [0, 1]
        assert ledger.all_done()
        assert ledger.lease("w0") == {"done": True}
        assert ledger.counts()["requeues"] == 0

    def test_outstanding_lease_means_wait_not_done(self, tmp_path):
        rundir, specs = _fake_rundir(tmp_path, n=1)
        ledger = SliceLedger(rundir, specs, lease_s=30.0)
        grant = ledger.lease("w0")
        assert ledger.lease("w1") == {"wait": True}
        ledger.commit(grant["lease_id"], 0, _out(rundir, 0))
        assert ledger.lease("w1") == {"done": True}

    def test_expiry_requeues_and_relets(self, tmp_path):
        rundir, specs = _fake_rundir(tmp_path, n=1)
        ledger = SliceLedger(rundir, specs, lease_s=0.05)
        grant = ledger.lease("w0")
        time.sleep(0.12)
        assert ledger.expire_scan() == 1
        counts = ledger.counts()
        assert counts["requeues"] == 1 and counts["workers_lost"] == 1
        regrant = ledger.lease("w1")
        assert regrant["slice"]["sid"] == grant["slice"]["sid"]
        assert regrant["lease_id"] != grant["lease_id"]

    def test_heartbeat_extends_lease(self, tmp_path):
        rundir, specs = _fake_rundir(tmp_path, n=1)
        ledger = SliceLedger(rundir, specs, lease_s=0.2)
        grant = ledger.lease("w0")
        deadline = time.monotonic() + 0.5
        while time.monotonic() < deadline:
            assert ledger.heartbeat("w0", grant["lease_id"])
            assert ledger.expire_scan() == 0
            time.sleep(0.05)
        # wrong holder never renews someone else's lease
        assert not ledger.heartbeat("w1", grant["lease_id"])

    def test_lapsed_publish_refused_then_duplicate_tolerated(self, tmp_path):
        rundir, specs = _fake_rundir(tmp_path, n=1)
        ledger = SliceLedger(rundir, specs, lease_s=0.05)
        stale = ledger.lease("w0")
        time.sleep(0.12)
        ledger.expire_scan()
        assert not ledger.heartbeat("w0", stale["lease_id"])
        manifest = _out(rundir, 0)
        refusal = ledger.commit(stale["lease_id"], 0, manifest, worker="w0")
        assert refusal == {"ok": False, "reason": "lease_expired"}
        # the requeued twin commits; the stale holder's late publish of
        # identical bytes is then a tolerated duplicate
        fresh = ledger.lease("w1")
        assert ledger.commit(fresh["lease_id"], 0, manifest, worker="w1") == {
            "ok": True
        }
        assert ledger.commit(stale["lease_id"], 0, manifest, worker="w0") == {
            "ok": True, "duplicate": True
        }

    def test_fingerprint_and_integrity_refusals(self, tmp_path):
        rundir, specs = _fake_rundir(tmp_path, n=1)
        ledger = SliceLedger(rundir, specs, lease_s=30.0)
        grant = ledger.lease("w0")
        bad_fam = dict(_out(rundir, 0), family_crc=999)
        assert ledger.commit(grant["lease_id"], 0, bad_fam)["reason"] == (
            "fingerprint_mismatch"
        )
        bad_crc = dict(_out(rundir, 0), crc=12345)
        resp = ledger.commit(grant["lease_id"], 0, bad_crc)
        assert not resp["ok"] and resp["reason"].startswith("output_integrity")
        assert not ledger.all_done()

    def test_worker_death_fast_path(self, tmp_path):
        rundir, specs = _fake_rundir(tmp_path, n=2)
        ledger = SliceLedger(rundir, specs, lease_s=30.0)
        g0 = ledger.lease("w0")
        ledger.lease("w1")
        ledger.note_worker_dead("w0")
        counts = ledger.counts()
        assert counts["requeues"] == 1 and counts["pending"] == 1
        assert not ledger.heartbeat("w0", g0["lease_id"])

    def test_restart_rescan_keeps_verified_manifests(self, tmp_path):
        """Crash-only coordinator: a fresh ledger over the same rundir
        trusts only manifests whose fingerprint matches AND whose output
        bytes still verify."""
        rundir, specs = _fake_rundir(tmp_path, n=2)
        ledger = SliceLedger(rundir, specs, lease_s=30.0)
        grant = ledger.lease("w0")
        sid = grant["slice"]["sid"]
        ledger.commit(grant["lease_id"], sid, _out(rundir, sid))

        reborn = SliceLedger(rundir, specs, lease_s=30.0)
        counts = reborn.counts()
        assert counts["done"] == 1 and counts["pending"] == 1
        assert reborn.lease("w0")["slice"]["sid"] != sid

        # tamper with the committed output: the next restart distrusts it
        out = os.path.join(rundir, "slices", slice_name(sid), "out.bam")
        with open(out, "wb") as fh:
            fh.write(b"bitrot")
        third = SliceLedger(rundir, specs, lease_s=30.0)
        assert third.counts()["done"] == 0


# ---------------------------------------------------------------------------
# coordinator wire ops (in-process server, real tcp)


class TestCoordinatorWire:
    @pytest.fixture()
    def served(self, tmp_path):
        rundir, specs = _fake_rundir(tmp_path, n=1)
        ledger = SliceLedger(rundir, specs, lease_s=30.0)
        server = Coordinator(
            ledger, {"doc": True}, addresses=["tcp:127.0.0.1:0"]
        )
        # graftlint: owned-thread -- test fixture accept loop, drained
        # in teardown
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        deadline = time.monotonic() + 10.0
        while not server.bound and time.monotonic() < deadline:
            time.sleep(0.01)
        assert server.bound
        yield server.bound[0], rundir, ledger
        server.request_drain()
        thread.join(timeout=10.0)

    def test_op_table(self, served):
        addr, rundir, _ledger = served
        assert transport.request(addr, {"op": "ping"})["pong"]
        joined = transport.request(
            addr, {"op": "elastic_join", "worker": "wt"}
        )
        assert joined["ok"] and joined["rundir"] == rundir
        assert joined["cfg"] == {"doc": True} and joined["slices"] == 1

        grant = transport.request(addr, {"op": "lease", "worker": "wt"})
        assert grant["ok"] and grant["slice"]["sid"] == 0
        hb = transport.request(
            addr,
            {"op": "heartbeat", "worker": "wt", "lease_id": grant["lease_id"]},
        )
        assert hb["ok"]
        status = transport.request(addr, {"op": "status"})
        assert status["leased"] == 1 and status["pending"] == 0

        manifest = _out(rundir, 0)
        pub = transport.request(addr, {
            "op": "publish", "worker": "wt",
            "lease_id": grant["lease_id"], "slice": 0, "manifest": manifest,
        })
        assert pub == {"ok": True}
        assert transport.request(addr, {"op": "lease", "worker": "wt"}) == {
            "ok": True, "done": True
        }

    def test_unknown_op_is_a_refusal(self, served):
        addr, _rundir, _ledger = served
        resp = transport.request(addr, {"op": "frobnicate"})
        assert not resp["ok"] and "unknown op" in resp["error"]

    def test_bad_publish_refused_over_wire(self, served):
        addr, rundir, _ledger = served
        grant = transport.request(addr, {"op": "lease", "worker": "wt"})
        bad = dict(_out(rundir, 0), family_crc=31337)
        resp = transport.request(addr, {
            "op": "publish", "worker": "wt",
            "lease_id": grant["lease_id"], "slice": 0, "manifest": bad,
        })
        assert resp == {"ok": False, "reason": "fingerprint_mismatch"}


# ---------------------------------------------------------------------------
# byte-identity + reconciliation (inline + in-process work_loop)


class TestByteIdentity:
    @pytest.mark.parametrize("slices", [1, 3, 4, 7])
    def test_inline_matches_single_process(self, swarm_env, tmp_path, slices):
        outdir = str(tmp_path / "out")
        cfg = swarm_env["cfg"]
        target, report = run_elastic(
            cfg, swarm_env["bam"], outdir, inline=True, slices=slices
        )
        assert _sha(target) == swarm_env["sp_sha"]
        assert report["ok"] and all(report["checks"].values())
        assert report["requeues"] == 0

    def test_counters_reconcile_with_single_process(self, swarm_env, tmp_path):
        """Summed per-slice StageStats equal the single-process run's
        content counters. 'batches' is excluded by design: slicing
        changes batch composition, never record content."""
        outdir = str(tmp_path / "out")
        _target, report = run_elastic(
            swarm_env["cfg"], swarm_env["bam"], outdir, inline=True, slices=4
        )
        content_keys = [
            k for k in merge_mod.SUMMABLE_STATS if k != "batches"
        ]
        for stage in ("molecular", "duplex"):
            sp = swarm_env["sp_stats"][stage]
            summed = report["stats"][stage]
            for key in content_keys:
                assert summed[key] == int(sp.get(key, 0)), (stage, key)
        assert report["records_split"] == swarm_env["records"]

    def test_work_loop_over_tcp(self, swarm_env, tmp_path, monkeypatch):
        """A real worker loop (join → lease → pipeline → publish) over
        tcp against a real coordinator, then the real merge: the full
        protocol path in one process."""
        monkeypatch.setenv(ENV_WORKER_ID, "wl0")
        monkeypatch.setenv(ENV_COORDINATOR_ADDR, "")
        outdir = str(tmp_path / "out")
        rundir = os.path.join(outdir, "elastic")
        os.makedirs(rundir, exist_ok=True)
        cfg = swarm_env["cfg"]
        specs = split_input(swarm_env["bam"], rundir, 3)
        ledger = SliceLedger(rundir, specs, lease_s=30.0)
        server = Coordinator(
            ledger, config_doc(cfg), addresses=["tcp:127.0.0.1:0"]
        )
        server.start_monitor()
        # graftlint: owned-thread -- test coordinator accept loop,
        # drained below
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        try:
            deadline = time.monotonic() + 10.0
            while not server.bound and time.monotonic() < deadline:
                time.sleep(0.01)
            processed = worker_mod.work_loop(server.bound[0], worker_id="wl0")
        finally:
            server.request_drain()
            thread.join(timeout=10.0)
        assert processed == 3
        target, report = merge_mod.finalize(
            cfg, swarm_env["bam"], outdir, specs, ledger.manifests()
        )
        assert report["ok"], report["checks"]
        assert _sha(target) == swarm_env["sp_sha"]
        for m in ledger.manifests().values():
            assert m["worker"] == "wl0"

    def test_stale_final_reset_recomputes_with_full_stats(
        self, swarm_env, tmp_path
    ):
        """A slice whose previous holder finished the pipeline but died
        before the manifest commit leaves a durable final in the work
        dir. Resuming past it would skip the stages whole (mtime rerun)
        and publish a stats-empty manifest that cannot reconcile — the
        reset must recompute and republish identical bytes WITH full
        ingest counters."""
        rundir = str(tmp_path / "run")
        specs = split_input(swarm_env["bam"], rundir, 3)
        first = worker_mod.process_slice(
            swarm_env["cfg"], rundir, specs[0], worker="wa"
        )
        assert first["stats"]["molecular"]["records_in"] > 0
        # the re-lease: same slice, no committed manifest, final present
        second = worker_mod.process_slice(
            swarm_env["cfg"], rundir, specs[0], worker="wb"
        )
        assert second["crc"] == first["crc"]
        assert second["buckets"] == first["buckets"]
        assert (
            second["stats"]["molecular"]["records_in"]
            == first["stats"]["molecular"]["records_in"]
        )

    def test_scope_refusals(self, swarm_env):
        cfg = dataclasses.replace(swarm_env["cfg"], aligner="bwameth")
        with pytest.raises(ElasticError, match="aligner"):
            run_elastic(cfg, swarm_env["bam"], "unused")
        cfg = dataclasses.replace(swarm_env["cfg"], methyl="cpg")
        with pytest.raises(ElasticError, match="methyl"):
            run_elastic(cfg, swarm_env["bam"], "unused")

    def test_config_doc_roundtrip(self, swarm_env):
        cfg = swarm_env["cfg"]
        assert config_from_doc(config_doc(cfg)) == cfg


# ---------------------------------------------------------------------------
# subprocess fleets (slow): cli elastic run, chaos kill, TLS join


def _elastic_env(tmp_path, **extra):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=REPO,
        BSSEQ_TPU_STATS=str(tmp_path / "elastic_ledger.jsonl"),
        BSSEQ_TPU_RETRY_BACKOFF_S="0.01",
    )
    env.pop("BSSEQ_TPU_FAILPOINTS", None)
    env.update(extra)
    return env


def _run_cli_elastic(swarm_env, tmp_path, *extra_args, env=None):
    outdir = str(tmp_path / "out")
    cp = subprocess.run(
        [sys.executable, "-m", "bsseqconsensusreads_tpu.cli",
         "elastic", "run",
         "--bam", swarm_env["bam"],
         "--reference", swarm_env["fasta"],
         "--outdir", outdir,
         *extra_args],
        capture_output=True, text=True, cwd=REPO,
        env=env or _elastic_env(tmp_path),
        timeout=900,
    )
    return cp, outdir


def _ledger_events(tmp_path):
    counts = {}
    with open(str(tmp_path / "elastic_ledger.jsonl")) as fh:
        for line in fh:
            ev = json.loads(line).get("event")
            counts[ev] = counts.get(ev, 0) + 1
    return counts


@pytest.mark.slow
class TestFleetSubprocess:
    def test_two_worker_fleet_matches_single_process(
        self, swarm_env, tmp_path
    ):
        cp, _outdir = _run_cli_elastic(
            swarm_env, tmp_path, "--workers", "2", "--slices", "4"
        )
        assert cp.returncode == 0, cp.stdout + cp.stderr
        out = json.loads(cp.stdout)
        assert _sha(out["target"]) == swarm_env["sp_sha"]
        assert out["report"]["ok"] and out["report"]["requeues"] == 0
        events = _ledger_events(tmp_path)
        assert events.get("elastic_worker_spawn") == 2
        assert events.get("elastic_slice_done") == 4
        assert events.get("elastic_run_complete") == 1

        # worker-scoped observe views line up per process
        ledger = str(tmp_path / "elastic_ledger.jsonl")
        s = ledger_tools.summarize_ledger(ledger)
        assert set(s.workers) >= {"w0", "w1"}
        done_per_worker = 0
        for wid in ("w0", "w1"):
            sw = ledger_tools.summarize_ledger(ledger, worker=wid)
            assert sw.worker == wid and not sw.problems
            done_per_worker += sw.events.get("elastic_slice_processed", 0)
        assert done_per_worker == 4

    def test_four_worker_fleet_matches_single_process(
        self, swarm_env, tmp_path
    ):
        """The acceptance gate: `--workers 4` byte-identical (SHA) to
        the single-process run."""
        cp, _outdir = _run_cli_elastic(
            swarm_env, tmp_path, "--workers", "4", "--slices", "8"
        )
        assert cp.returncode == 0, cp.stdout + cp.stderr
        out = json.loads(cp.stdout)
        assert _sha(out["target"]) == swarm_env["sp_sha"]
        assert out["report"]["ok"], out["report"]["checks"]
        assert out["report"]["records"] == 2 * N_FAMILIES  # R1+R2 per family

    def test_worker_kill_requeues_and_bytes_hold(self, swarm_env, tmp_path):
        """Chaos leg: w0 dies mid-slice (failpoint exit:9 on its second
        slice pickup); the slice requeues, a respawn or the survivor
        finishes it, and the merged bytes still equal single-process."""
        cp, _outdir = _run_cli_elastic(
            swarm_env, tmp_path,
            "--workers", "2", "--slices", "4",
            "--worker-failpoints", "w0:elastic_slice=exit:9@hit=2",
        )
        assert cp.returncode == 0, cp.stdout + cp.stderr
        out = json.loads(cp.stdout)
        assert _sha(out["target"]) == swarm_env["sp_sha"]
        report = out["report"]
        assert report["ok"], report["checks"]
        assert report["requeues"] >= 1 and report["workers_lost"] >= 1
        events = _ledger_events(tmp_path)
        assert events.get("slice_requeued", 0) >= 1
        assert events.get("worker_lost", 0) >= 1
        assert events.get("elastic_worker_spawn", 0) >= 3  # w0 respawned
        assert events.get("failpoint_fired", 0) >= 1

    def test_tls_join(self, swarm_env, tmp_path):
        """TLS on the coordinator socket: the spawned workers inherit
        the cert env and join over TLS; bytes still match."""
        if shutil.which("openssl") is None:
            pytest.skip("openssl not available")
        cert = str(tmp_path / "elastic.crt")
        key = str(tmp_path / "elastic.key")
        gen = subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048",
             "-keyout", key, "-out", cert, "-days", "1", "-nodes",
             "-subj", "/CN=127.0.0.1"],
            capture_output=True, timeout=120,
        )
        assert gen.returncode == 0, gen.stderr
        env = _elastic_env(
            tmp_path,
            BSSEQ_TPU_SERVE_TLS_CERT=cert,
            BSSEQ_TPU_SERVE_TLS_KEY=key,
        )
        cp, _outdir = _run_cli_elastic(
            swarm_env, tmp_path, "--workers", "2", "--slices", "2", env=env
        )
        assert cp.returncode == 0, cp.stdout + cp.stderr
        out = json.loads(cp.stdout)
        assert _sha(out["target"]) == swarm_env["sp_sha"]
        assert out["report"]["ok"]
