"""Standalone utility subcommands (sort / zipper / sam-to-fastq /
filter-mapped): the reference invokes fgbio SortBam, fgbio ZipperBams,
Picard SamToFastq, and samtools view -F 4 as separate tools
(main.snake.py:67,106,118,152); these CLIs are their drop-in equivalents
over the framework's record ops."""

import gzip

import numpy as np
import pytest

from bsseqconsensusreads_tpu.cli import main as cli_main
from bsseqconsensusreads_tpu.io.bam import (
    BamHeader,
    BamReader,
    BamRecord,
    BamWriter,
    CMATCH,
    FUNMAP,
)
from bsseqconsensusreads_tpu.pipeline.record_ops import (
    coordinate_key,
    name_key,
    template_coordinate_key,
    zipper_bams_stream,
)


@pytest.fixture()
def scrambled_bam(tmp_path):
    rng = np.random.default_rng(9)
    header = BamHeader(
        "@HD\tVN:1.6\tSO:unsorted\n", [("chrA", 5000), ("chrB", 5000)]
    )
    records = []
    for i in range(40):
        flag = 99 if i % 2 == 0 else 147
        rec = BamRecord(
            qname=f"q{i % 13}", flag=flag, ref_id=int(rng.integers(0, 2)),
            pos=int(rng.integers(0, 4000)), mapq=60,
            cigar=[(CMATCH, 20)], next_ref_id=0, next_pos=0,
            seq="A" * 20, qual=bytes([30] * 20),
        )
        rec.set_tag("MI", str(i % 7), "Z")
        records.append(rec)
    records.append(BamRecord(  # one unmapped record for filter-mapped
        qname="un", flag=FUNMAP, ref_id=-1, pos=-1, mapq=0, cigar=[],
        next_ref_id=-1, next_pos=-1, seq="A" * 10, qual=bytes([30] * 10),
    ))
    path = str(tmp_path / "scrambled.bam")
    with BamWriter(path, header) as w:
        w.write_all(records)
    return path, records


@pytest.mark.parametrize(
    "order,key",
    [
        ("coordinate", coordinate_key),
        ("name", name_key),
        ("template-coordinate", template_coordinate_key),
    ],
)
def test_sort_orders(scrambled_bam, tmp_path, order, key):
    path, records = scrambled_bam
    out = str(tmp_path / f"sorted_{order}.bam")
    assert cli_main(["sort", "-i", path, "-o", out, "--order", order]) == 0
    with BamReader(out) as r:
        got = list(r)
        hd = next(
            ln for ln in r.header.text.splitlines() if ln.startswith("@HD")
        )
    assert len(got) == len(records)
    keys = [key(rec) for rec in got]
    assert keys == sorted(keys)
    # the @HD SO line is rewritten like samtools sort / fgbio SortBam do
    want_so = {
        "coordinate": "SO:coordinate",
        "name": "SO:queryname",
        "template-coordinate": "SO:unsorted\tSS:template-coordinate",
    }[order]
    assert want_so in hd, hd


def test_filter_mapped(scrambled_bam, tmp_path):
    path, records = scrambled_bam
    out = str(tmp_path / "mapped.bam")
    assert cli_main(["filter-mapped", "-i", path, "-o", out]) == 0
    with BamReader(out) as r:
        got = list(r)
    assert len(got) == len(records) - 1
    assert all(not rec.flag & FUNMAP for rec in got)


def test_sam_to_fastq(tmp_path):
    header = BamHeader("@HD\tVN:1.6\tSO:unsorted\n", [("chrA", 1000)])
    records = []
    for i in range(6):
        for flag in (99, 147):
            records.append(BamRecord(
                qname=f"t{i}", flag=flag, ref_id=0, pos=100 + i, mapq=60,
                cigar=[(CMATCH, 12)], next_ref_id=0, next_pos=100,
                seq="ACGTACGTACGT", qual=bytes(range(30, 42)),
            ))
    path = str(tmp_path / "pairs.bam")
    with BamWriter(path, header) as w:
        w.write_all(records)
    fq1, fq2 = str(tmp_path / "r1.fq.gz"), str(tmp_path / "r2.fq.gz")
    assert cli_main(
        ["sam-to-fastq", "-i", path, "--fq1", fq1, "--fq2", fq2]
    ) == 0
    lines1 = gzip.open(fq1, "rt").read().splitlines()
    lines2 = gzip.open(fq2, "rt").read().splitlines()
    assert len(lines1) == len(lines2) == 6 * 4
    # in-step pairing: same template at the same offset in both files
    # (names carry the /1 and /2 mate suffixes)
    assert [ln[1:].rsplit("/", 1)[0] for ln in lines1[::4]] == [
        ln[1:].rsplit("/", 1)[0] for ln in lines2[::4]
    ]
    assert all(ln.endswith("/1") for ln in lines1[::4])
    assert all(ln.endswith("/2") for ln in lines2[::4])


def test_zipper_matches_library(tmp_path):
    rng = np.random.default_rng(4)
    header = BamHeader("@HD\tVN:1.6\tSO:unsorted\n", [("chrA", 5000)])
    aligned, unaligned = [], []
    for i in range(10):
        for flag_a, flag_u in ((99, 77), (147, 141)):
            seq = "".join("ACGT"[b] for b in rng.integers(0, 4, size=20))
            aligned.append(BamRecord(
                qname=f"z{i}", flag=flag_a, ref_id=0,
                pos=100 + 37 * i, mapq=60, cigar=[(CMATCH, 20)],
                next_ref_id=0, next_pos=100, seq=seq,
                qual=bytes([30] * 20),
            ))
            un = BamRecord(
                qname=f"z{i}", flag=flag_u, ref_id=-1, pos=-1, mapq=0,
                cigar=[], next_ref_id=-1, next_pos=-1, seq=seq,
                qual=bytes([30] * 20),
            )
            un.set_tag("MI", str(i), "Z")
            un.set_tag("RX", "AC-GT", "Z")
            unaligned.append(un)
    pa = str(tmp_path / "aligned.bam")
    pu = str(tmp_path / "unaligned.bam")
    with BamWriter(pa, header) as w:
        w.write_all(aligned)
    with BamWriter(pu, header) as w:
        w.write_all(unaligned)
    out = str(tmp_path / "zipped.bam")
    assert cli_main(
        ["zipper", "-i", pa, "--unmapped", pu, "-o", out]
    ) == 0
    with BamReader(out) as r:
        got = [(rec.qname, rec.flag, dict(rec.tags)) for rec in r]
        assert "SO:coordinate" in r.header.text
    want = [
        (rec.qname, rec.flag, dict(rec.tags))
        for rec in zipper_bams_stream(iter(aligned), iter(unaligned), header)
    ]
    assert got == want and len(got) == 20
    assert all("MI" in tags and "RX" in tags for _, _, tags in got)
