"""Sort-free bucketed emit vs the external-sort engines (ISSUE 12).

sort_engine=bucket replaces the k-way merge tail with per-bucket in-core
sorts concatenated in plan order — its ONLY correctness claim is byte
identity with the python/native external sorts, for any bucket count and
any hostpool worker count. These tests pin that matrix over the
adversarial shapes named in the issue (records straddling a bucket
boundary, unmapped/ref_id=-1, empty contigs, the single-bucket
degenerate plan, heavy positional skew), across both item packings
(single blobs = the python emitter, RawRecords blocks = the native
emitter), through the spill path, under the bucket_spill failpoint,
through the durable two-phase checkpointed finalize (damaged-run
replay), through the fused inter-stage stream, and through the parallel
BGZF codec tier.
"""

from __future__ import annotations

import hashlib
import os
import random

import numpy as np
import pytest

from bsseqconsensusreads_tpu.io import native, wirepack
from bsseqconsensusreads_tpu.io.bam import (
    BamHeader,
    BamRecord,
    BamWriter,
    CMATCH,
    RawRecords,
    encode_record,
)
from bsseqconsensusreads_tpu.pipeline import bucketemit, extsort
from bsseqconsensusreads_tpu.pipeline.extsort import raw_coordinate_key

HEADER = BamHeader("@HD\tVN:1.6\n", [("chr1", 1 << 20), ("chr2", 1 << 20)])

#: zero-length contigs interleaved with real ones: the planner must not
#: waste boundaries on them and the router must not misassign neighbours
HEADER_EMPTY = BamHeader(
    "@HD\tVN:1.6\n",
    [("chrE0", 0), ("chr1", 1 << 20), ("chrE1", 0), ("chr2", 1 << 20),
     ("chrE2", 0)],
)

#: identity reference: the native engine when its libs are built (the CI
#: image builds them), else the python engine — the two are pinned
#: byte-identical to each other by tests/test_nativesort.py
REF_ENGINE = (
    "native" if (wirepack.available() and native.available()) else "python"
)


def _rec(rng: random.Random, qname: str, ref_id: int, pos: int) -> bytes:
    ln = rng.choice((8, 12, 20))
    r = BamRecord(
        qname=qname,
        flag=rng.choice((99, 147, 83, 163, 0, 4)),
        ref_id=ref_id,
        pos=pos,
        mapq=60,
        cigar=[(CMATCH, ln)],
        seq="ACGT" * (ln // 4),
        qual=bytes([rng.randrange(2, 40)] * ln),
    )
    return encode_record(r)


def _case_blobs(case: str) -> tuple[list[bytes], BamHeader, int]:
    """(encoded records, header, bucket count) for one adversarial shape."""
    rng = random.Random(hash(case) & 0xFFFF)
    blobs: list[bytes] = []
    if case == "straddle":
        # clusters of SAME-qname records at boundary-1 / boundary /
        # boundary+1 around every interior plan boundary: equal full keys
        # must never split across buckets
        plan = bucketemit.BucketPlan.from_header(HEADER, 8)
        for key in plan.boundaries[1:]:
            ref, pos = key >> bucketemit.REF_SHIFT, key & ((1 << 31) - 1)
            for d in (-1, 0, 0, 0, 1):
                for _ in range(4):
                    blobs.append(_rec(rng, f"q{key}", ref, pos + d))
        for _ in range(400):
            blobs.append(_rec(rng, f"f{rng.randrange(40)}",
                              rng.randrange(2), rng.randrange(1 << 20)))
        return blobs, HEADER, 8
    if case == "unmapped":
        # every sentinel combination: fully unmapped, mapped ref with
        # pos=-1 (buckets WITHIN its contig, not at the end), pos with
        # ref=-1 — mixed with mapped records
        for i in range(600):
            ref, pos = rng.choice(
                ((-1, -1), (-1, rng.randrange(1000)),
                 (0, -1), (1, -1),
                 (0, rng.randrange(1 << 20)), (1, rng.randrange(1 << 20)))
            )
            blobs.append(_rec(rng, f"u{i % 30}", ref, pos))
        return blobs, HEADER, 8
    if case == "empty_contigs":
        for i in range(600):
            blobs.append(_rec(rng, f"e{i % 25}", rng.choice((1, 3)),
                              rng.randrange(1 << 20)))
        return blobs, HEADER_EMPTY, 8
    if case == "single_bucket":
        for i in range(500):
            blobs.append(_rec(rng, f"s{i % 20}", rng.randrange(2),
                              rng.choice((-1, rng.randrange(1 << 20)))))
        return blobs, HEADER, 1
    if case == "skew":
        # 90% of records in a 100bp window of chr2: one hot bucket among
        # 64 mostly-empty ones, with heavy key ties
        for i in range(900):
            if i % 10:
                blobs.append(_rec(rng, f"k{i % 15}", 1,
                                  1000 + rng.randrange(100)))
            else:
                blobs.append(_rec(rng, f"k{i % 15}", rng.randrange(2),
                                  rng.randrange(1 << 20)))
        return blobs, HEADER, 64
    raise AssertionError(case)


def _pack_raw(blobs: list[bytes], seed: int) -> list[RawRecords]:
    """Chunk single blobs into RawRecords blocks (the native emitter's
    item shape) without reordering."""
    rng = random.Random(seed)
    items, i = [], 0
    while i < len(blobs):
        k = rng.randrange(1, 9)
        items.append(RawRecords(b"".join(blobs[i : i + k]),
                                len(blobs[i : i + k])))
        i += k
    return items


def _engine_bytes(items, engine: str, buffer_records: int, tmp_path,
                  tag: str, header: BamHeader = HEADER, buckets: int = 0,
                  metrics=None) -> bytes:
    path = str(tmp_path / f"{tag}_{engine}.bam")
    with BamWriter(path, header) as w:
        extsort.external_sort_raw_to_writer(
            iter(items), w, header, workdir=str(tmp_path),
            buffer_records=buffer_records, engine=engine,
            sort_buckets=buckets, metrics=metrics,
        )
    with open(path, "rb") as fh:
        return fh.read()


CASES = ("straddle", "unmapped", "empty_contigs", "single_bucket", "skew")


class TestPlanUnit:
    def test_resolve_buckets(self, monkeypatch):
        monkeypatch.delenv(bucketemit.ENV_BUCKETS, raising=False)
        assert bucketemit.resolve_buckets() == bucketemit.DEFAULT_BUCKETS
        assert bucketemit.resolve_buckets(7) == 7
        monkeypatch.setenv(bucketemit.ENV_BUCKETS, "3")
        assert bucketemit.resolve_buckets(7) == 3
        monkeypatch.setenv(bucketemit.ENV_BUCKETS, "junk")
        assert bucketemit.resolve_buckets(7) == bucketemit.DEFAULT_BUCKETS

    def test_bucket_key_orders_like_sort_key(self):
        """Combined-key order must equal the (ref, pos) prefix order of
        raw_coordinate_key — including the INDEPENDENT unmapped
        sentinels (a mapped-ref/pos=-1 record sorts within its contig)."""
        rng = random.Random(5)
        blobs = [
            _rec(rng, "k", ref, pos)
            for ref, pos in ((-1, -1), (0, 5), (0, -1), (1, 0), (-1, 7),
                             (1, -1), (0, 0), (1, (1 << 20) - 1))
        ]
        by_bucket_key = sorted(blobs, key=bucketemit.blob_bucket_key)
        by_sort_key = sorted(blobs, key=lambda b: raw_coordinate_key(b)[:2])
        assert [raw_coordinate_key(b)[:2] for b in by_bucket_key] == [
            raw_coordinate_key(b)[:2] for b in by_sort_key
        ]

    def test_plan_shape_and_ownership(self):
        plan = bucketemit.BucketPlan.from_header(HEADER, 8)
        assert plan.boundaries[0] == 0
        assert plan.boundaries == sorted(set(plan.boundaries))
        assert 2 <= plan.nbuckets <= 8
        # every key has exactly one owner, in ascending bucket order
        keys = [0, 1, 5000, (1 << bucketemit.REF_SHIFT) + 3,
                (bucketemit.UNMAPPED_SENTINEL << bucketemit.REF_SHIFT)
                + bucketemit.UNMAPPED_SENTINEL]
        owners = [plan.bucket_of(k) for k in keys]
        assert owners == sorted(owners)
        assert all(0 <= b < plan.nbuckets for b in owners)

    def test_plan_degenerate_and_empty_contigs(self):
        assert bucketemit.BucketPlan.from_header(HEADER, 1).boundaries == [0]
        empty = BamHeader("@HD\tVN:1.6\n", [("chrE", 0)])
        assert bucketemit.BucketPlan.from_header(empty, 8).boundaries == [0]
        plan = bucketemit.BucketPlan.from_header(HEADER_EMPTY, 8)
        assert plan.boundaries[0] == 0 and plan.nbuckets >= 2

    def test_plan_validation(self):
        with pytest.raises(ValueError, match="start at key 0"):
            bucketemit.BucketPlan([5, 10])
        with pytest.raises(ValueError, match="strictly ascending"):
            bucketemit.BucketPlan([0, 10, 10])


class TestBucketIdentityMatrix:
    """The issue's core matrix: every adversarial shape x hostpool worker
    count x item packing, byte-identical to the external-sort engine."""

    @pytest.mark.parametrize("workers", (0, 1, 4))
    @pytest.mark.parametrize("case", CASES)
    def test_identity(self, tmp_path, monkeypatch, case, workers):
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", str(workers))
        blobs, header, buckets = _case_blobs(case)
        ref = _engine_bytes(blobs, REF_ENGINE, 10_000, tmp_path, "ref",
                            header)
        for packing in ("blobs", "raw"):
            items = blobs if packing == "blobs" else _pack_raw(blobs, 5)
            got = _engine_bytes(items, "bucket", 10_000, tmp_path,
                                f"{packing}{workers}", header, buckets)
            assert hashlib.sha256(got).hexdigest() == hashlib.sha256(
                ref
            ).hexdigest(), f"{case}/{packing}/workers={workers}"

    @pytest.mark.parametrize("workers", (0, 4))
    def test_spill_path_identity(self, tmp_path, monkeypatch, workers):
        """A tiny buffer forces repeated largest-bucket spills (the hot
        skew bucket accumulates several runs) — the per-bucket run merge
        must still reproduce the reference bytes."""
        from bsseqconsensusreads_tpu.utils import observe

        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", str(workers))
        blobs, header, buckets = _case_blobs("skew")
        ref = _engine_bytes(blobs, REF_ENGINE, 10_000, tmp_path, "ref",
                            header)
        metrics = observe.Metrics()
        got = _engine_bytes(blobs, "bucket", 150, tmp_path, f"sp{workers}",
                            header, buckets, metrics=metrics)
        assert got == ref
        assert metrics.counters.get("bucket_spill_runs", 0) > 0
        assert "sort_write.bucket_spill" in metrics.seconds

    def test_python_routing_fallback(self, tmp_path, monkeypatch):
        """With the native sweeps stubbed out the pure-python router must
        produce the same bytes (the no-native-libs deployment)."""
        blobs, header, buckets = _case_blobs("straddle")
        ref = _engine_bytes(_pack_raw(blobs, 9), "bucket", 10_000, tmp_path,
                            "nat", header, buckets)
        monkeypatch.setattr(bucketemit, "_use_native", lambda: False)
        got = _engine_bytes(_pack_raw(blobs, 9), "bucket", 10_000, tmp_path,
                            "py", header, buckets)
        assert got == ref

    def test_sub_phase_attribution_lands(self, tmp_path):
        from bsseqconsensusreads_tpu.utils import observe

        metrics = observe.Metrics()
        blobs, header, buckets = _case_blobs("straddle")
        _engine_bytes(blobs, "bucket", 10_000, tmp_path, "attr", header,
                      buckets, metrics=metrics)
        secs = metrics.seconds
        assert "sort_write.bucket_route" in secs
        assert "sort_write.bucket_sort" in secs
        assert "sort_write.bucket_concat" in secs
        assert metrics.counters["bucket_count"] >= 2
        assert metrics.counters["bucket_records"] == len(blobs)
        # dotted sub-phases must not inflate the host phase summary
        summary = metrics.phase_summary(1.0)
        assert summary["host_s"] == pytest.approx(
            secs.get("sort_write", 0.0), abs=2e-3
        )


class TestResolveEngine:
    def test_bucket_accepted_and_env_override(self, monkeypatch):
        monkeypatch.delenv("BSSEQ_TPU_SORT_ENGINE", raising=False)
        assert extsort.resolve_sort_engine("bucket") == "bucket"
        monkeypatch.setenv("BSSEQ_TPU_SORT_ENGINE", "bucket")
        assert extsort.resolve_sort_engine("native") == "bucket"
        monkeypatch.delenv("BSSEQ_TPU_SORT_ENGINE")
        with pytest.raises(ValueError, match="unknown sort engine"):
            extsort.resolve_sort_engine("frobnicate")


class TestSpillFault:
    def test_spill_io_error_retried_byte_identical(self, tmp_path):
        """One injected IO error on a bucket run write: retried whole,
        byte-identical output, retry counted."""
        from bsseqconsensusreads_tpu.faults import failpoints
        from bsseqconsensusreads_tpu.utils import observe

        blobs, header, buckets = _case_blobs("skew")
        clean = _engine_bytes(blobs, "bucket", 150, tmp_path, "clean",
                              header, buckets)
        metrics = observe.Metrics()
        failpoints.arm("bucket_spill=io_error:times=1")
        try:
            faulted = _engine_bytes(blobs, "bucket", 150, tmp_path, "flt",
                                    header, buckets, metrics=metrics)
        finally:
            failpoints.disarm()
        assert faulted == clean
        assert metrics.counters.get("batches_retried", 0) == 1


class TestDurableFinalize:
    def _blobs(self) -> list[bytes]:
        rng = random.Random(41)
        return [
            _rec(rng, f"d{i % 20}", rng.choice((-1, 0, 1)),
                 rng.choice((-1, rng.randrange(1 << 20))))
            for i in range(500)
        ]

    def _checkpoint(self, tmp_path, blobs):
        from bsseqconsensusreads_tpu.pipeline.checkpoint import (
            BatchCheckpoint,
        )

        target = str(tmp_path / "out.bam")
        ck = BatchCheckpoint(target, HEADER, every=2, fingerprint={"p": 1})
        ck.write_batches(
            [RawRecords(b"".join(blobs[i : i + 25]), 25)]
            for i in range(0, len(blobs), 25)
        )
        return ck, target

    def test_finalize_matches_reference(self, tmp_path):
        blobs = self._blobs()
        ref = _engine_bytes(blobs, REF_ENGINE, 10_000, tmp_path, "ref")
        ck, target = self._checkpoint(tmp_path, blobs)
        n = bucketemit.finalize_checkpoint(ck, HEADER,
                                           workdir=str(tmp_path))
        assert n == len(blobs)
        with open(target, "rb") as fh:
            assert fh.read() == ref
        assert not os.path.exists(target + ".bucketruns")

    def test_crash_in_finalize_replays_only_damaged(self, tmp_path):
        """Crash mid-Phase B, corrupt one bucket run on disk: the resume
        verifies every run CRC, replays ONLY the damaged bucket from the
        durable shards, and still produces the reference bytes."""
        from bsseqconsensusreads_tpu.faults import failpoints
        from bsseqconsensusreads_tpu.pipeline.checkpoint import (
            BatchCheckpoint,
        )
        from bsseqconsensusreads_tpu.utils import observe

        blobs = self._blobs()
        ref = _engine_bytes(blobs, REF_ENGINE, 10_000, tmp_path, "ref")
        ck, target = self._checkpoint(tmp_path, blobs)
        failpoints.arm("bucket_finalize=raise:RuntimeError@hit=2")
        try:
            with pytest.raises(RuntimeError):
                bucketemit.finalize_checkpoint(ck, HEADER,
                                               workdir=str(tmp_path))
        finally:
            failpoints.disarm()
        rundir = target + ".bucketruns"
        doc = bucketemit._load_manifest(rundir)
        assert doc is not None and doc["complete"]
        # flip a byte in the first registered bucket run
        victim = next(
            os.path.join(rundir, runs[0][0])
            for runs in doc["buckets"] if runs
        )
        data = bytearray(open(victim, "rb").read())
        data[len(data) // 2] ^= 0xFF
        with open(victim, "wb") as fh:
            fh.write(data)

        ck2 = BatchCheckpoint(target, HEADER, every=2,
                              fingerprint={"p": 1})
        metrics = observe.Metrics()
        n = bucketemit.finalize_checkpoint(ck2, HEADER,
                                           workdir=str(tmp_path),
                                           metrics=metrics)
        assert n == len(blobs)
        assert metrics.counters.get("bucket_replayed", 0) >= 1
        with open(target, "rb") as fh:
            assert fh.read() == ref

    def test_stale_manifest_discarded(self, tmp_path):
        """A manifest whose fingerprint (e.g. bucket plan) no longer
        matches must be discarded, not spliced: Phase A redoes cleanly."""
        blobs = self._blobs()
        ck, target = self._checkpoint(tmp_path, blobs)
        rundir = target + ".bucketruns"
        os.makedirs(rundir, exist_ok=True)
        bucketemit._save_manifest(
            rundir,
            {"fingerprint": {"stale": True}, "boundaries": [0],
             "complete": True, "buckets": [[]]},
        )
        ref = _engine_bytes(blobs, REF_ENGINE, 10_000, tmp_path, "ref")
        bucketemit.finalize_checkpoint(ck, HEADER, workdir=str(tmp_path))
        with open(target, "rb") as fh:
            assert fh.read() == ref


def _pipeline_digests(tmp_path, tag: str, records, name: str, genome: str,
                      **cfg_kw) -> dict[str, str]:
    """Run the full self-aligned pipeline; digest EVERY output BAM (the
    molecular intermediate rides the sort too)."""
    from bsseqconsensusreads_tpu.config import FrameworkConfig
    from bsseqconsensusreads_tpu.pipeline.stages import run_pipeline
    from bsseqconsensusreads_tpu.utils.testing import write_fasta

    wd = tmp_path / tag
    wd.mkdir()
    fa = str(wd / "g.fa")
    write_fasta(fa, name, genome)
    header = BamHeader("@HD\tVN:1.6\tSO:coordinate\n", [(name, len(genome))])
    inbam = str(wd / "in.bam")
    with BamWriter(inbam, header) as w:
        for r in records:
            w.write(r)
    cfg = FrameworkConfig(
        genome_dir=str(wd), genome_fasta_file_name="g.fa", tmp=str(wd),
        aligner="self", grouping="coordinate", batch_families=7,
        sort_buffer_records=40, **cfg_kw,
    )
    run_pipeline(cfg, inbam, outdir=str(wd / "out"))
    out = {}
    for f in sorted(os.listdir(wd / "out")):
        if f.endswith(".bam"):
            with open(wd / "out" / f, "rb") as fh:
                out[f] = hashlib.sha256(fh.read()).hexdigest()
    return out


class TestPipelineIdentity:
    """Both consensus stages through the real pipeline: the bucket
    engine, the checkpointed bucket engine, and the fused inter-stage
    stream must all reproduce the reference engine's BAMs exactly."""

    @pytest.fixture(scope="class")
    def family_input(self):
        from bsseqconsensusreads_tpu.utils.testing import (
            make_grouped_bam_records,
            random_genome,
        )

        rng = np.random.default_rng(61)
        name, genome = random_genome(rng, 6000)
        _, records = make_grouped_bam_records(rng, name, genome,
                                              n_families=12)
        return name, genome, records

    def test_engine_and_fused_identity(self, tmp_path, family_input):
        name, genome, records = family_input
        ref = _pipeline_digests(tmp_path, "ref", records, name, genome,
                                sort_engine=REF_ENGINE)
        bucket = _pipeline_digests(tmp_path, "bkt", records, name, genome,
                                   sort_engine="bucket")
        fused = _pipeline_digests(tmp_path, "fus", records, name, genome,
                                  sort_engine="bucket",
                                  stream_interstage=True)
        assert bucket == ref
        assert fused == ref

    def test_checkpointed_bucket_identity(self, tmp_path, family_input):
        name, genome, records = family_input
        ref = _pipeline_digests(tmp_path, "ref", records, name, genome,
                                sort_engine=REF_ENGINE)
        ck = _pipeline_digests(tmp_path, "ck", records, name, genome,
                               sort_engine="bucket", checkpoint_every=2)
        assert ck == ref

    def test_fused_fallback_is_loud_and_identical(self, tmp_path,
                                                  family_input, capfd):
        """stream_interstage on a non-fusable config (checkpointing on)
        must fall back to the two-stage path LOUDLY and still produce
        identical bytes."""
        name, genome, records = family_input
        ref = _pipeline_digests(tmp_path, "ref", records, name, genome,
                                sort_engine="bucket", checkpoint_every=2)
        fb = _pipeline_digests(tmp_path, "fb", records, name, genome,
                               sort_engine="bucket", checkpoint_every=2,
                               stream_interstage=True)
        assert fb == ref
        assert "interstage" in capfd.readouterr().err


class TestPbgzfCodec:
    def test_parallel_bytes_identical_to_serial(self, tmp_path):
        """Any worker count, any chunking: PBgzfWriter's output is the
        serial BgzfWriter's, byte for byte (same block cutting, same
        deflate, in-order delivery)."""
        from bsseqconsensusreads_tpu.io.bgzf import BgzfWriter
        from bsseqconsensusreads_tpu.io.pbgzf import PBgzfWriter

        rng = random.Random(3)
        chunks = [
            os.urandom(rng.choice((10, 1000, 70_000))) for _ in range(40)
        ] + [b"A" * 200_000]
        serial = str(tmp_path / "s.bgzf")
        with BgzfWriter.open(serial) as w:
            for c in chunks:
                w.write(c)
        for workers in (1, 2, 4):
            par = str(tmp_path / f"p{workers}.bgzf")
            with PBgzfWriter.open(par, workers=workers) as w:
                for c in chunks:
                    w.write(c)
            assert open(par, "rb").read() == open(serial, "rb").read()

    def test_default_workers_env_gate(self, monkeypatch):
        from bsseqconsensusreads_tpu.io import pbgzf

        monkeypatch.setenv("BSSEQ_TPU_PBGZF", "3")
        assert pbgzf.default_workers() == 3
        monkeypatch.setenv("BSSEQ_TPU_PBGZF", "0")
        assert pbgzf.default_workers() == 0
        monkeypatch.delenv("BSSEQ_TPU_PBGZF", raising=False)
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "1")
        assert pbgzf.default_workers() == 0
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "4")
        assert pbgzf.default_workers() == 4

    def test_pbgzf_metrics_attribution(self, tmp_path):
        from bsseqconsensusreads_tpu.io.pbgzf import PBgzfWriter
        from bsseqconsensusreads_tpu.utils import observe

        metrics = observe.Metrics()
        path = str(tmp_path / "m.bgzf")
        with PBgzfWriter.open(path, workers=2, metrics=metrics) as w:
            w.write(os.urandom(300_000))
        assert metrics.counters["pbgzf_workers"] == 2
        assert metrics.counters["pbgzf_blocks"] >= 4
        assert "sort_write.deflate" in metrics.seconds
