"""graftfleet tests: transport framing, router placement, fleet lifecycle.

* transport — one framing per transport (bounded JSONL on unix, u32
  length-prefix on tcp), every hostile shape (oversized/empty/garbage/
  truncated frame) a typed `TransportError` refusal with a `.reason`,
  never a crash and never an unbounded read; a protocol server answers
  refusals with the `guard` key on the wire;
* placement — affinity pins a repeat input to the replica that saw it
  last, fresh inputs go to the least-outstanding replica, the pin moves
  with a requeue; counters (`jobs_routed`/`jobs_requeued`/
  `affinity_hits`/`replica_restarts`) reconcile against per-replica
  admissions;
* fleet lifecycle — a real 2-replica fleet behind `cli route` serves
  byte-identical outputs over tcp, survives a SIGKILL-grade replica
  death with requeue+respawn, optionally speaks TLS, and warm-starts
  from the shared compile cache across fleet boots.

In-process tests (socketpairs, fake fleets) stay tier-1; subprocess
fleet tests are marked slow, same split as tests/test_serve.py.
"""

import hashlib
import json
import os
import shutil
import socket
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from bsseqconsensusreads_tpu import cli
from bsseqconsensusreads_tpu.faults.guard import GuardError
from bsseqconsensusreads_tpu.io.bam import BamWriter
from bsseqconsensusreads_tpu.serve import router as router_mod
from bsseqconsensusreads_tpu.serve import transport
from bsseqconsensusreads_tpu.serve.router import Router, RouterServer
from bsseqconsensusreads_tpu.serve.server import ProtocolServer
from bsseqconsensusreads_tpu.utils.testing import make_grouped_bam_records

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

GENOME = "".join(
    "ACGT"[i] for i in np.random.default_rng(7).integers(0, 4, size=2000)
)


def _grouped_bam(path: str, seed: int, n_families: int = 6,
                 read_len: int = 40) -> None:
    header, records = make_grouped_bam_records(
        np.random.default_rng(seed), f"chr{seed % 97}", GENOME,
        n_families=n_families, reads_per_strand=(2, 3), read_len=read_len,
    )
    with BamWriter(path, header) as w:
        for r in records:
            w.write(r)


def _sha(path: str) -> str:
    with open(path, "rb") as fh:
        return hashlib.sha256(fh.read()).hexdigest()


def _standalone(inp: str, out: str) -> str:
    rc = cli.main(
        ["molecular", "-i", inp, "-o", out, "--batching", "sequential"]
    )
    assert rc == 0
    return _sha(out)


# ---------------------------------------------------------------------------
# address grammar


class TestAddressGrammar:
    def test_bare_path_and_unix_scheme_are_unix(self):
        assert transport.parse_address("/tmp/x.sock") == (
            "unix", "/tmp/x.sock"
        )
        assert transport.parse_address("unix:/tmp/x.sock") == (
            "unix", "/tmp/x.sock"
        )
        assert not transport.is_tcp("/tmp/x.sock")

    def test_tcp_form(self):
        assert transport.parse_address("tcp:127.0.0.1:8641") == (
            "tcp", "127.0.0.1", 8641
        )
        assert transport.is_tcp("tcp:localhost:0")

    @pytest.mark.parametrize(
        "bad",
        ["", "unix:", "tcp:", "tcp:nohost", "tcp::123", "tcp:h:",
         "tcp:h:notaport", "tcp:h:70000"],
    )
    def test_bad_addresses_are_typed_refusals(self, bad):
        with pytest.raises(transport.TransportError) as ei:
            transport.parse_address(bad)
        assert ei.value.reason == "bad_address"
        # typed both ways: guard contract AND socket-failure handlers
        assert isinstance(ei.value, GuardError)
        assert isinstance(ei.value, ConnectionError)


# ---------------------------------------------------------------------------
# wire framing (socketpair: no server process involved)


class TestFraming:
    def _pair(self):
        a, b = socket.socketpair()
        a.settimeout(5.0)
        b.settimeout(5.0)
        return a, b

    @pytest.mark.parametrize("kind", ["unix", "tcp"])
    def test_roundtrip_parity_across_transports(self, kind):
        """The same payload crosses both framings unchanged — a client
        cannot tell the transports apart above the frame layer."""
        payload = {"op": "submit", "spec": {"input": "x", "n": [1, 2, 3]}}
        a, b = self._pair()
        try:
            transport.send_message(a, kind, payload)
            assert transport.recv_message(b, kind) == payload
        finally:
            a.close()
            b.close()

    def test_clean_eof_is_none(self):
        a, b = self._pair()
        a.close()
        try:
            assert transport.recv_message(b, "tcp") is None
            c, d = self._pair()
            c.close()
            assert transport.recv_message(d, "unix") is None
            d.close()
        finally:
            b.close()

    def test_oversized_declared_length_refused_before_body(self):
        """The length header is the admission gate: a hostile declared
        size is refused with ZERO payload bytes buffered."""
        a, b = self._pair()
        try:
            a.sendall(struct.pack("!I", transport.MAX_FRAME + 1))
            with pytest.raises(transport.TransportError) as ei:
                transport.recv_message(b, "tcp")
            assert ei.value.reason == "oversized_frame"
        finally:
            a.close()
            b.close()

    def test_empty_frame_refused(self):
        a, b = self._pair()
        try:
            a.sendall(struct.pack("!I", 0))
            with pytest.raises(transport.TransportError) as ei:
                transport.recv_message(b, "tcp")
            assert ei.value.reason == "empty_frame"
        finally:
            a.close()
            b.close()

    def test_garbage_body_refused(self):
        a, b = self._pair()
        try:
            body = b"\xff\xfe not json at all"
            a.sendall(struct.pack("!I", len(body)) + body)
            with pytest.raises(transport.TransportError) as ei:
                transport.recv_message(b, "tcp")
            assert ei.value.reason == "bad_json"
        finally:
            a.close()
            b.close()

    def test_non_object_json_refused(self):
        a, b = self._pair()
        try:
            body = b"[1, 2, 3]"
            a.sendall(struct.pack("!I", len(body)) + body)
            with pytest.raises(transport.TransportError) as ei:
                transport.recv_message(b, "tcp")
            assert ei.value.reason == "bad_json"
        finally:
            a.close()
            b.close()

    def test_truncated_body_refused(self):
        a, b = self._pair()
        try:
            a.sendall(struct.pack("!I", 100) + b"only ten b")
            a.close()
            with pytest.raises(transport.TransportError) as ei:
                transport.recv_message(b, "tcp")
            assert ei.value.reason == "truncated_frame"
        finally:
            b.close()

    def test_unix_line_without_newline_is_bounded(self):
        """A peer that never sends '\\n' is refused at max_bytes, not
        buffered forever — the PR 8 JSONL reader is bounded now."""
        a, b = self._pair()
        try:
            a.sendall(b"x" * 8192)
            with pytest.raises(transport.TransportError) as ei:
                transport.recv_message(b, "unix", max_bytes=1024)
            assert ei.value.reason == "oversized_frame"
        finally:
            a.close()
            b.close()


# ---------------------------------------------------------------------------
# protocol server refusals on the wire (in-process server thread)


class _EchoServer(ProtocolServer):
    def _dispatch(self, req: dict) -> dict:
        return {"ok": True, "echo": req}

    def _on_drain(self) -> None:
        pass


@pytest.fixture
def echo_server():
    srv = _EchoServer(addresses=["tcp:127.0.0.1:0"])
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    deadline = time.monotonic() + 10
    while not srv.bound and time.monotonic() < deadline:
        time.sleep(0.01)
    assert srv.bound, "server never bound"
    yield srv
    srv.request_drain()
    t.join(timeout=10)


class TestServerRefusals:
    def test_tcp_request_roundtrip(self, echo_server):
        resp = transport.request(
            echo_server.bound[0], {"op": "ping", "k": 1}, timeout=5.0
        )
        assert resp == {"ok": True, "echo": {"op": "ping", "k": 1}}

    def test_hostile_length_header_answered_with_guard_reason(
        self, echo_server
    ):
        sock, kind = transport.connect(echo_server.bound[0], timeout=5.0)
        try:
            sock.sendall(struct.pack("!I", transport.MAX_FRAME + 7))
            resp = transport.recv_message(sock, kind)
        finally:
            sock.close()
        assert resp["ok"] is False
        assert resp["guard"] == "oversized_frame"

    def test_garbage_frame_answered_with_guard_reason(self, echo_server):
        sock, kind = transport.connect(echo_server.bound[0], timeout=5.0)
        try:
            body = b"<html>not a protocol message</html>"
            sock.sendall(struct.pack("!I", len(body)) + body)
            resp = transport.recv_message(sock, kind)
        finally:
            sock.close()
        assert resp["ok"] is False
        assert resp["guard"] == "bad_json"


# ---------------------------------------------------------------------------
# router placement (fake fleet + monkeypatched forward: no sockets)


class _FakeReplica:
    def __init__(self, rid: str):
        self.rid = rid
        self.address = f"tcp:127.0.0.1:1{rid[1:]}"
        self.proc = None
        self.generation = 0
        self.up = True

    @property
    def supervised(self) -> bool:
        return True

    def alive(self) -> bool:
        return self.up


class _FakeFleet:
    def __init__(self, n: int = 2):
        self.replicas = [_FakeReplica(f"r{i}") for i in range(n)]
        self.restarted: list[str] = []

    def alive(self):
        return [r for r in self.replicas if r.alive()]

    def lookup(self, rid):
        for r in self.replicas:
            if r.rid == rid:
                return r
        return None

    def restart(self, replica):
        self.restarted.append(replica.rid)
        replica.generation += 1
        replica.up = True


@pytest.fixture
def routed(monkeypatch, tmp_path):
    """A Router over a 2-replica fake fleet whose forward path records
    placements instead of opening sockets."""
    placements: list[tuple[str, str]] = []  # (replica_id, input)
    seq = {"n": 0}

    def fake_request(address, payload, timeout=0.0):
        if payload.get("op") == "submit":
            seq["n"] += 1
            rid = next(
                r.rid for r in fleet.replicas if r.address == address
            )
            placements.append((rid, payload["spec"]["input"]))
            return {"ok": True,
                    "job": {"id": f"j{seq['n']:04d}", "state": "queued"}}
        return {"ok": True, "stats": {"jobs": [], "counters": {}}}

    fleet = _FakeFleet(2)
    monkeypatch.setattr(router_mod._transport, "request", fake_request)
    router = Router(replicas=fleet)  # no launch(): no monitor thread
    inputs = []
    for k in range(2):
        p = str(tmp_path / f"in{k}.bin")
        with open(p, "wb") as fh:
            fh.write(bytes([k]) * 64)
        inputs.append(p)
    return router, fleet, placements, inputs


class TestRouterPlacement:
    def test_repeat_input_pins_fresh_input_balances(self, routed):
        router, fleet, placements, inputs = routed
        for _ in range(3):
            assert router.submit({"input": inputs[0], "output": "a"})["ok"]
        # all three on one replica: 1 fresh placement + 2 affinity hits
        assert len({rid for rid, _ in placements}) == 1
        pinned = placements[0][0]
        assert router.counters["affinity_hits"] == 2
        # a fresh input lands on the OTHER replica (least outstanding)
        assert router.submit({"input": inputs[1], "output": "b"})["ok"]
        assert placements[-1][0] != pinned
        assert router.counters["jobs_routed"] == 4
        assert router.counters["jobs_requeued"] == 0

    def test_no_affinity_places_purely_by_depth(
        self, routed, monkeypatch
    ):
        router, fleet, placements, inputs = routed
        router.affinity_enabled = False
        for _ in range(2):
            assert router.submit({"input": inputs[0], "output": "a"})["ok"]
        # same input, but depth placement spreads it across both
        assert {rid for rid, _ in placements} == {"r0", "r1"}
        assert router.counters["affinity_hits"] == 0

    def test_replica_death_requeues_moves_pin_and_respawns(self, routed):
        router, fleet, placements, inputs = routed
        for _ in range(2):
            assert router.submit({"input": inputs[0], "output": "a"})["ok"]
        dead = fleet.lookup(placements[0][0])
        survivor = next(r.rid for r in fleet.replicas if r is not dead)
        dead.up = False
        router._handle_death(dead)
        # both open jobs re-placed on the survivor, pin moved with them
        assert [rid for rid, _ in placements[2:]] == [survivor, survivor]
        jobs = list(router._jobs.values())
        assert all(j.replica_id == survivor for j in jobs)
        assert all(j.requeues == 1 for j in jobs)
        assert router.counters["jobs_requeued"] == 2
        # jobs_routed counts every placement, requeues included
        assert router.counters["jobs_routed"] == 4
        assert router.counters["replica_restarts"] == 1
        assert fleet.restarted == [dead.rid]
        assert router._affinity[jobs[0].digest] == survivor

    def test_no_live_replicas_is_a_refusal_not_a_crash(self, routed):
        router, fleet, _, inputs = routed
        for r in fleet.replicas:
            r.up = False
        resp = router.submit({"input": inputs[0], "output": "a"})
        assert resp["ok"] is False
        assert "no live replicas" in resp["error"]

    def test_router_server_answers_ping_and_unknown_op(self, routed):
        router, _, _, _ = routed
        srv = RouterServer(router, addresses=["tcp:127.0.0.1:0"])
        assert srv._dispatch({"op": "ping"}) == {
            "ok": True, "pong": True, "router": True
        }
        resp = srv._dispatch({"op": "frobnicate"})
        assert resp["ok"] is False and "unknown op" in resp["error"]


# ---------------------------------------------------------------------------
# real fleet (subprocess): identity, handoff, TLS, warm compile cache


def _fleet_env(tmp_path, **extra):
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
        BSSEQ_TPU_STATS=str(tmp_path / "fleet_ledger.jsonl"),
        BSSEQ_TPU_RETRY_BACKOFF_S="0.01",
    )
    env.update(extra)
    return env


def _spawn_route(tmp_path, extra_args=(), env=None):
    rundir = str(tmp_path / "rundir")
    os.makedirs(rundir, exist_ok=True)
    ready = os.path.join(rundir, "router.addr")
    proc = subprocess.Popen(
        [sys.executable, "-m", "bsseqconsensusreads_tpu.cli", "route",
         "--replicas", "2",
         "--address", "tcp:127.0.0.1:0",
         "--ready-file", ready,
         "--rundir", rundir,
         "--batch-families", "4",
         *extra_args],
        env=env or _fleet_env(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    deadline = time.monotonic() + 240
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise AssertionError(
                f"router died rc={proc.returncode}: "
                f"{proc.stderr.read().decode()[-2000:]}"
            )
        if os.path.exists(ready):
            address = open(ready).read().strip().splitlines()[0]
            try:
                if transport.request(
                    address, {"op": "ping"}, timeout=2.0
                ).get("ok"):
                    return proc, address
            except (OSError, ConnectionError):
                pass
        time.sleep(0.1)
    proc.kill()
    raise AssertionError("router never became ready")


def _drain_route(proc, address) -> int:
    try:
        transport.request(
            address, {"op": "drain", "timeout": 300}, timeout=360
        )
    except (OSError, ConnectionError):
        pass
    return proc.wait(timeout=120)


def _ledger_event_count(ledger: str, event: str) -> int:
    n = 0
    with open(ledger) as fh:
        for line in fh:
            if json.loads(line).get("event") == event:
                n += 1
    return n


@pytest.mark.slow
class TestFleetProcess:
    def test_tcp_parity_affinity_and_reconciliation(self, tmp_path):
        """2 distinct tenants x 2 submits through a 2-replica fleet:
        every output byte-identical to the standalone CLI, repeat
        inputs hit affinity, and the router's jobs_routed reconciles
        against both the per-replica job counts and the fleet ledger's
        job_admitted lines."""
        inputs, refs = [], []
        for k in range(2):
            inp = str(tmp_path / f"in{k}.bam")
            _grouped_bam(inp, seed=910 + k)
            inputs.append(inp)
            refs.append(_standalone(inp, str(tmp_path / f"ref{k}.bam")))
        proc, address = _spawn_route(tmp_path)
        try:
            outs, jobs = [], []
            for n, k in enumerate([0, 0, 1, 1]):
                out = str(tmp_path / f"out{n}.bam")
                outs.append((out, refs[k]))
                resp = transport.request(address, {
                    "op": "submit",
                    "spec": {"input": inputs[k], "output": out},
                })
                assert resp["ok"], resp
                jobs.append(resp["job"]["id"])
            for jid in jobs:
                resp = transport.request(
                    address, {"op": "wait", "job": jid, "timeout": 120},
                    timeout=180,
                )
                assert resp["job"]["state"] == "done", resp
            stats = transport.request(
                address, {"op": "fleet"}, timeout=30
            )["stats"]
            rc = _drain_route(proc, address)
            assert rc == 0
            for out, ref in outs:
                assert _sha(out) == ref
            counters = stats["counters"]
            assert counters["jobs_routed"] == 4
            assert counters["jobs_requeued"] == 0
            # the second submit of each input rode the affinity pin
            assert counters["affinity_hits"] >= 2
            # reconciliation, both ways: replica-reported job counts and
            # the shared ledger's admission lines both sum to jobs_routed
            per_replica = sum(
                e.get("jobs", 0) for e in stats["replicas"].values()
            )
            assert per_replica == counters["jobs_routed"]
            ledger = str(tmp_path / "fleet_ledger.jsonl")
            assert _ledger_event_count(
                ledger, "job_admitted"
            ) == counters["jobs_routed"]
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)

    def test_replica_kill_handoff_byte_identical(self, tmp_path):
        """r0 is armed to die mid-stream on its first life; every
        tenant completes byte-identical on the survivor (requeue), the
        dead replica respawns, and the drained router exits 0."""
        inp = str(tmp_path / "in.bam")
        _grouped_bam(inp, seed=920, n_families=8)
        ref = _standalone(inp, str(tmp_path / "ref.bam"))
        proc, address = _spawn_route(
            tmp_path,
            extra_args=["--replica-failpoints",
                        "r0:fleet_replica_exit=exit:9@batch=1"],
        )
        try:
            outs, jobs = [], []
            for n in range(3):
                out = str(tmp_path / f"out{n}.bam")
                outs.append(out)
                resp = transport.request(address, {
                    "op": "submit", "spec": {"input": inp, "output": out},
                })
                assert resp["ok"], resp
                jobs.append(resp["job"]["id"])
            for jid in jobs:
                resp = transport.request(
                    address, {"op": "wait", "job": jid, "timeout": 180},
                    timeout=240,
                )
                assert resp["job"]["state"] == "done", resp
            stats = transport.request(
                address, {"op": "fleet"}, timeout=30
            )["stats"]
            rc = _drain_route(proc, address)
            assert rc == 0
            for out in outs:
                assert _sha(out) == ref
            counters = stats["counters"]
            assert counters["jobs_requeued"] >= 1
            assert counters["replica_restarts"] >= 1
            # every placement (initial + requeue) is a routed job
            assert counters["jobs_routed"] == 3 + counters["jobs_requeued"]
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)

    @pytest.mark.skipif(
        shutil.which("openssl") is None, reason="openssl not available"
    )
    def test_tls_roundtrip_byte_identical(self, tmp_path, monkeypatch):
        """A serve replica behind TLS (env-armed cert/key): ping +
        submit + wait over the encrypted tcp transport, output
        byte-identical to the standalone CLI."""
        cert = str(tmp_path / "cert.pem")
        key = str(tmp_path / "key.pem")
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048",
             "-keyout", key, "-out", cert, "-days", "1", "-nodes",
             "-subj", "/CN=127.0.0.1"],
            check=True, capture_output=True, timeout=120,
        )
        inp = str(tmp_path / "in.bam")
        _grouped_bam(inp, seed=930)
        ref = _standalone(inp, str(tmp_path / "ref.bam"))
        ready = str(tmp_path / "serve.addr")
        proc = subprocess.Popen(
            [sys.executable, "-m", "bsseqconsensusreads_tpu.cli", "serve",
             "--address", "tcp:127.0.0.1:0", "--ready-file", ready,
             "--batch-families", "4"],
            env=_fleet_env(
                tmp_path,
                BSSEQ_TPU_SERVE_TLS_CERT=cert,
                BSSEQ_TPU_SERVE_TLS_KEY=key,
            ),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        # the CLIENT reads the same env pair to verify the server cert
        monkeypatch.setenv("BSSEQ_TPU_SERVE_TLS_CERT", cert)
        monkeypatch.setenv("BSSEQ_TPU_SERVE_TLS_KEY", key)
        try:
            deadline = time.monotonic() + 120
            address = None
            while time.monotonic() < deadline:
                if proc.poll() is not None:
                    raise AssertionError(
                        f"server died rc={proc.returncode}: "
                        f"{proc.stderr.read().decode()[-2000:]}"
                    )
                if os.path.exists(ready):
                    address = open(ready).read().strip().splitlines()[0]
                    try:
                        if transport.request(
                            address, {"op": "ping"}, timeout=2.0
                        ).get("ok"):
                            break
                    except (OSError, ConnectionError):
                        pass
                time.sleep(0.1)
            else:
                raise AssertionError("TLS server never became ready")
            out = str(tmp_path / "out.bam")
            resp = transport.request(address, {
                "op": "submit", "spec": {"input": inp, "output": out},
            })
            assert resp["ok"], resp
            resp = transport.request(
                address,
                {"op": "wait", "job": resp["job"]["id"], "timeout": 120},
                timeout=180,
            )
            assert resp["job"]["state"] == "done", resp
            transport.request(
                address, {"op": "drain", "timeout": 120}, timeout=180
            )
            assert proc.wait(timeout=60) == 0
            assert _sha(out) == ref
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)

    def test_compile_cache_warm_start_across_fleet_boots(self, tmp_path):
        """Two fleet boots sharing BSSEQ_TPU_COMPILE_CACHE_DIR: the
        first boot's compiles are misses; the second boot's replicas
        start warm (cache hits in the second ledger)."""
        inp = str(tmp_path / "in.bam")
        _grouped_bam(inp, seed=940)
        cache = str(tmp_path / "xla_cache")

        def boot_and_run(tag):
            ledger = str(tmp_path / f"ledger_{tag}.jsonl")
            env = _fleet_env(
                tmp_path,
                BSSEQ_TPU_STATS=ledger,
                BSSEQ_TPU_COMPILE_CACHE_DIR=cache,
            )
            proc, address = _spawn_route(tmp_path, env=env)
            try:
                out = str(tmp_path / f"out_{tag}.bam")
                resp = transport.request(address, {
                    "op": "submit", "spec": {"input": inp, "output": out},
                })
                assert resp["ok"], resp
                resp = transport.request(
                    address,
                    {"op": "wait", "job": resp["job"]["id"],
                     "timeout": 120},
                    timeout=180,
                )
                assert resp["job"]["state"] == "done", resp
                assert _drain_route(proc, address) == 0
            finally:
                if proc.poll() is None:
                    proc.kill()
                    proc.wait(timeout=30)
            counts = {"compile_cache_hit": 0, "compile_cache_miss": 0}
            with open(ledger) as fh:
                for line in fh:
                    d = json.loads(line)
                    for k in counts:
                        counts[k] += int(d.get(k, 0) or 0)
            return counts

        c1 = boot_and_run("cold")
        assert c1["compile_cache_miss"] > 0, c1
        c2 = boot_and_run("warm")
        assert c2["compile_cache_hit"] > 0, c2
