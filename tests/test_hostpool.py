"""Host-parallel batch engine (parallel.hostpool + pipeline.calling):
byte-identical output for any BSSEQ_TPU_HOST_WORKERS, graftfault
semantics inside host-pool tasks, the overlap-pool × wire-round-robin
composition (MULTICHIP-style, on the multi-device dryrun path the
conftest forces), the loud round_robin_conflict fallback, and the
extsort background spill writer.

The engine exists for multi-core TPU-attached hosts (the round-5 scale
artifacts measured the rawize pass serializing the duplex stage); on
this suite's CPU backend it is forced via BSSEQ_TPU_HOST_WORKERS and
asserted for pure equivalence — the determinism guarantee IS the
feature under test.
"""

from __future__ import annotations

import gc
import json
import os
import threading

import numpy as np
import pytest

from bsseqconsensusreads_tpu.faults import failpoints
from bsseqconsensusreads_tpu.io.bam import (
    BamHeader,
    BamWriter,
    write_items,
)
from bsseqconsensusreads_tpu.models.params import ConsensusParams
from bsseqconsensusreads_tpu.parallel import hostpool
from bsseqconsensusreads_tpu.pipeline.calling import (
    StageStats,
    call_duplex_batches,
    call_molecular_batches,
)
from bsseqconsensusreads_tpu.utils.testing import (
    make_aligned_duplex_group,
    make_grouped_bam_records,
    random_genome,
)


@pytest.fixture(autouse=True)
def _disarm():
    yield
    failpoints.disarm()


@pytest.fixture(scope="module")
def molecular_corpus():
    rng = np.random.default_rng(41)
    name, genome = random_genome(rng, 20000)
    # reads_per_strand from 1 exercises the T==1 singleton path, which
    # rides the host pool whole (hp_vote_emit)
    header, records = make_grouped_bam_records(
        rng, name, genome, n_families=36, reads_per_strand=(1, 3)
    )
    records.sort(key=lambda r: (r.ref_id, r.pos))
    return header, records


@pytest.fixture(scope="module")
def duplex_corpus():
    rng = np.random.default_rng(43)
    name, genome = random_genome(rng, 18000)
    records = []
    for fam in range(32):
        records.extend(
            make_aligned_duplex_group(
                rng, name, genome, fam, 60 + fam * 120, 70,
                softclip=2 if fam % 4 == 0 else 0,
            )
        )
    records.sort(key=lambda r: (r.ref_id, r.pos))
    return name, genome, records


def _mol_bytes(corpus, tmp_path, tag, stats=None, **kw):
    header, records = corpus
    stats = stats if stats is not None else StageStats()
    out = str(tmp_path / f"mol_{tag}.bam")
    kw.setdefault("mesh", None)
    batches = call_molecular_batches(
        iter(list(records)), params=ConsensusParams(min_reads=1),
        mode="self", batch_families=7, grouping="coordinate",
        stats=stats, **kw,
    )
    with BamWriter(out, header, engine="python") as w:
        for b in batches:
            write_items(w, b)
    return open(out, "rb").read(), stats


def _dup_bytes(corpus, tmp_path, tag, stats=None, **kw):
    name, genome, records = corpus
    stats = stats if stats is not None else StageStats()
    out = str(tmp_path / f"dup_{tag}.bam")
    kw.setdefault("mesh", None)
    batches = call_duplex_batches(
        iter(list(records)), lambda n, s, e: genome[s:e], [name],
        mode="self", batch_families=8, grouping="coordinate",
        stats=stats, **kw,
    )
    header = BamHeader("@HD\tVN:1.6\tSO:coordinate\n", [(name, len(genome))])
    with BamWriter(out, header, engine="python") as w:
        for b in batches:
            write_items(w, b)
    return open(out, "rb").read(), stats


class TestHostWorkers:
    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "3")
        assert hostpool.host_workers() == 3

    def test_env_zero_disables(self, monkeypatch):
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "0")
        assert hostpool.host_workers() == 0
        assert hostpool.make_pool() is None

    def test_env_negative_clamps_to_zero(self, monkeypatch):
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "-2")
        assert hostpool.host_workers() == 0

    def test_bad_env_falls_back_to_default(self, monkeypatch):
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "lots")
        cores = os.cpu_count() or 1
        assert hostpool.host_workers() == min(4, max(0, cores - 1))

    def test_default_is_min_4_cores_minus_1(self, monkeypatch):
        monkeypatch.delenv("BSSEQ_TPU_HOST_WORKERS", raising=False)
        cores = os.cpu_count() or 1
        assert hostpool.host_workers() == min(4, max(0, cores - 1))

    def test_pool_decision_is_ledgered(self, tmp_path, monkeypatch):
        sink = str(tmp_path / "l.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "2")
        pool = hostpool.make_pool(stage="molecular")
        assert pool is not None and pool.workers == 2
        pool.shutdown()
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "0")
        assert hostpool.make_pool(stage="molecular") is None
        events = [json.loads(line) for line in open(sink)]
        kinds = [e["event"] for e in events]
        assert "host_pool_enabled" in kinds
        disabled = [e for e in events if e["event"] == "host_pool_disabled"]
        assert disabled and "explicit disable" in disabled[0]["reason"]


class TestByteIdentity:
    """The acceptance bar: output bytes identical under
    BSSEQ_TPU_HOST_WORKERS in {0, 1, 4} for both mini pipelines —
    ordered retirement + shadow-stat merge proven end to end."""

    @pytest.mark.parametrize("workers", ["1", "4"])
    def test_molecular_matches_inline(
        self, molecular_corpus, tmp_path, monkeypatch, workers
    ):
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "0")
        inline, st0 = _mol_bytes(molecular_corpus, tmp_path, "w0")
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", workers)
        got, st = _mol_bytes(molecular_corpus, tmp_path, f"w{workers}")
        assert got == inline and len(inline) > 200
        assert st.batches == st0.batches
        assert st.consensus_out == st0.consensus_out
        assert st.families == st0.families
        assert st.skipped_families == st0.skipped_families
        assert st.metrics.counters.get("host_pool_workers") == int(workers)

    @pytest.mark.parametrize("workers", ["1", "4"])
    def test_duplex_matches_inline(
        self, duplex_corpus, tmp_path, monkeypatch, workers
    ):
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "0")
        inline, st0 = _dup_bytes(duplex_corpus, tmp_path, "w0")
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", workers)
        got, st = _dup_bytes(duplex_corpus, tmp_path, f"w{workers}")
        assert got == inline and len(inline) > 200
        assert st.batches == st0.batches
        assert st.consensus_out == st0.consensus_out
        assert st.families == st0.families

    def test_molecular_wire_transport_matches(
        self, molecular_corpus, tmp_path, monkeypatch
    ):
        """Worker-side slim-wire fetch + count recompute + emit must
        still be byte-identical when the whole retire rides the host
        pool."""
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "0")
        inline, _ = _mol_bytes(
            molecular_corpus, tmp_path, "wire0", transport="wire"
        )
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "3")
        got, _ = _mol_bytes(
            molecular_corpus, tmp_path, "wire3", transport="wire"
        )
        assert got == inline

    def test_composes_with_overlap_pool(
        self, duplex_corpus, tmp_path, monkeypatch
    ):
        """Overlap workers (device dispatch/fetch) + host workers (emit)
        stacked: still byte-identical, and the host pool's join path is
        the one retiring ('stall' accounted on the main thread)."""
        monkeypatch.setenv("BSSEQ_TPU_OVERLAP_THREADS", "0")
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "0")
        inline, _ = _dup_bytes(duplex_corpus, tmp_path, "ov0")
        monkeypatch.setenv("BSSEQ_TPU_OVERLAP_THREADS", "2")
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "2")
        got, st = _dup_bytes(duplex_corpus, tmp_path, "ov2")
        assert got == inline
        assert "stall" in st.metrics.seconds

    def test_early_close_shuts_pool_down(self, duplex_corpus, monkeypatch):
        """Abandoning the batch generator mid-stream must wind down the
        host pool (no bsseq-host threads leaked)."""
        name, genome, records = duplex_corpus
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "2")
        before = {t.name for t in threading.enumerate()}
        batches = call_duplex_batches(
            iter(list(records)), lambda n, s, e: genome[s:e], [name],
            mode="self", batch_families=5, grouping="coordinate",
            stats=StageStats(), mesh=None,
        )
        next(batches)
        batches.close()
        leaked = {
            t.name
            for t in threading.enumerate()
            if t.name.startswith("bsseq-host") and t.is_alive()
        } - before
        assert not leaked


class TestHostpoolFaults:
    """graftfault semantics carry over into host-pool tasks: the
    hostpool_task failpoint fires INSIDE the retried unit."""

    def test_task_failpoint_retries_byte_identical(
        self, molecular_corpus, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "0")
        want, _ = _mol_bytes(molecular_corpus, tmp_path, "fp_ref")
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "2")
        monkeypatch.setenv("BSSEQ_TPU_RETRY_BACKOFF_S", "0.01")
        failpoints.arm("hostpool_task=raise:RuntimeError:times=1")
        stats = StageStats()
        got, _ = _mol_bytes(molecular_corpus, tmp_path, "fp", stats=stats)
        assert got == want
        assert stats.batches_retried >= 1
        assert stats.batches_recovered >= 1

    def test_persistent_dispatch_failure_degrades_under_hostpool(
        self, duplex_corpus, tmp_path, monkeypatch
    ):
        """A persistently failing device dispatch still degrades to the
        host twin with the host pool active — byte-identical."""
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "0")
        want, _ = _dup_bytes(duplex_corpus, tmp_path, "deg_ref")
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "2")
        monkeypatch.setenv("BSSEQ_TPU_RETRY_BACKOFF_S", "0.01")
        failpoints.arm("dispatch_kernel=raise:RuntimeError@batch=2")
        stats = StageStats()
        got, _ = _dup_bytes(duplex_corpus, tmp_path, "deg", stats=stats)
        assert got == want
        assert stats.batches_degraded >= 1

    def test_io_error_in_task_retries(
        self, duplex_corpus, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "0")
        want, _ = _dup_bytes(duplex_corpus, tmp_path, "io_ref")
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "1")
        monkeypatch.setenv("BSSEQ_TPU_RETRY_BACKOFF_S", "0.01")
        failpoints.arm("hostpool_task=io_error:times=2")
        stats = StageStats()
        got, _ = _dup_bytes(duplex_corpus, tmp_path, "io", stats=stats)
        assert got == want and stats.batches_retried >= 1


class TestComposition:
    """Overlap pool × _WireRoundRobin on the multi-device dryrun path
    (MULTICHIP-style; conftest forces 8 host-platform devices): no
    silent (None, 0) disable, exactly-once retire, no leaked wire
    buffers."""

    def _mesh(self):
        import jax

        if jax.device_count() < 2:
            pytest.skip("needs >1 device")
        from bsseqconsensusreads_tpu.parallel.mesh import make_mesh

        return make_mesh(n_data=min(4, jax.device_count()), n_reads=1)

    def test_composed_wire_mc_byte_identical_no_leak(
        self, molecular_corpus, tmp_path, monkeypatch
    ):
        import jax

        mesh = self._mesh()
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "0")
        monkeypatch.delenv("BSSEQ_TPU_OVERLAP_THREADS", raising=False)
        want, st0 = _mol_bytes(molecular_corpus, tmp_path, "cmp_ref")
        monkeypatch.setenv("BSSEQ_TPU_OVERLAP_THREADS", "2")

        def run(stats):
            return _mol_bytes(
                molecular_corpus, tmp_path, "cmp_mc", stats=stats,
                transport="wire", mesh=mesh,
            )[0]

        run(StageStats())  # warm jit/device caches before the leak census
        gc.collect()
        baseline = len(jax.live_arrays())
        stats = StageStats()
        got = run(stats)
        # byte-identical and stat-identical => every batch retired
        # exactly once through the composed pipeline
        assert got == want
        assert stats.batches == st0.batches
        assert stats.consensus_out == st0.consensus_out
        assert stats.metrics.counters.get("overlap_rr_composed") == 1
        assert stats.metrics.counters.get("overlap_pool_workers", 0) >= 2
        assert "overlap_pool_disabled" not in stats.metrics.counters
        gc.collect()
        assert len(jax.live_arrays()) <= baseline

    def test_composed_duplex_wire_mc_with_hostpool(
        self, duplex_corpus, tmp_path, monkeypatch
    ):
        """All three engines stacked on the duplex stage: round-robin
        wire dispatch on overlap workers, rawize/emit on host workers —
        byte-identical to the fully inline run."""
        from bsseqconsensusreads_tpu.ops.refstore import RefStore

        mesh = self._mesh()
        name, genome, _ = duplex_corpus
        store = RefStore([name], seqs=[genome])
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "0")
        monkeypatch.delenv("BSSEQ_TPU_OVERLAP_THREADS", raising=False)
        want, _ = _dup_bytes(duplex_corpus, tmp_path, "3x_ref")
        monkeypatch.setenv("BSSEQ_TPU_OVERLAP_THREADS", "2")
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "2")
        stats = StageStats()
        got, _ = _dup_bytes(
            duplex_corpus, tmp_path, "3x", stats=stats,
            transport="wire", refstore=store, mesh=mesh,
        )
        assert got == want
        assert stats.metrics.counters.get("overlap_rr_composed") == 1

    def test_zero_worker_fallback_is_loud(
        self, molecular_corpus, tmp_path, monkeypatch
    ):
        """The one remaining (None, 0) branch on a multi-device path
        must report reason 'round_robin_conflict' — never silent
        (ISSUE 4 satellite; VERDICT weak #6)."""
        mesh = self._mesh()
        sink = str(tmp_path / "rrc.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        monkeypatch.setenv("BSSEQ_TPU_OVERLAP_THREADS", "0")
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "0")
        stats = StageStats()
        got, _ = _mol_bytes(
            molecular_corpus, tmp_path, "rrc", stats=stats,
            transport="wire", mesh=mesh,
        )
        monkeypatch.delenv("BSSEQ_TPU_STATS")
        monkeypatch.delenv("BSSEQ_TPU_OVERLAP_THREADS")
        want, _ = _mol_bytes(molecular_corpus, tmp_path, "rrc_ref")
        assert got == want
        assert stats.metrics.counters.get("overlap_pool_disabled") == 1
        events = [json.loads(line) for line in open(sink)]
        disabled = [
            e for e in events if e["event"] == "overlap_pool_disabled"
        ]
        assert disabled
        assert disabled[0]["reason"].startswith("round_robin_conflict")


class TestSpillWriter:
    """pipeline.extsort's double-buffered background spill writer
    (gated on the same BSSEQ_TPU_HOST_WORKERS knob)."""

    def _sorted_blobs(self, n=900, seed=5):
        from bsseqconsensusreads_tpu.io.bam import encode_record
        from bsseqconsensusreads_tpu.utils.testing import (
            make_grouped_bam_records,
        )

        rng = np.random.default_rng(seed)
        name, genome = random_genome(rng, 30000)
        header, records = make_grouped_bam_records(
            rng, name, genome, n_families=n // 4
        )
        rng.shuffle(records)
        return header, [encode_record(r) for r in records]

    def test_background_writer_output_identical(
        self, tmp_path, monkeypatch
    ):
        from bsseqconsensusreads_tpu.pipeline.extsort import (
            external_sort_raw,
        )

        header, blobs = self._sorted_blobs()
        monkeypatch.setenv("BSSEQ_TPU_VERIFY_SPILLS", "1")
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "0")
        want = list(external_sort_raw(
            iter(blobs), header, workdir=str(tmp_path), buffer_records=64,
        ))
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "2")
        got = list(external_sort_raw(
            iter(blobs), header, workdir=str(tmp_path), buffer_records=64,
        ))
        assert got == want and len(want) == len(blobs)

    def test_background_writes_ride_the_writer_thread(
        self, tmp_path, monkeypatch
    ):
        """Ledger 'spill' events must come from the bsseq-spill thread
        (the writer actually moved off the stream), and the CRC verify
        contract (PR 3) must hold at merge open."""
        from bsseqconsensusreads_tpu.pipeline.extsort import (
            external_sort_raw,
        )

        sink = str(tmp_path / "spill.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        monkeypatch.setenv("BSSEQ_TPU_VERIFY_SPILLS", "1")
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "1")
        header, blobs = self._sorted_blobs(seed=6)
        out = list(external_sort_raw(
            iter(blobs), header, workdir=str(tmp_path), buffer_records=64,
        ))
        assert len(out) == len(blobs)
        spills = [
            json.loads(line)
            for line in open(sink)
            if '"spill"' in line
        ]
        spills = [e for e in spills if e.get("event") == "spill"]
        assert spills
        assert all(
            e.get("thread", "").startswith("bsseq-spill") for e in spills
        )

    def test_spill_io_error_retries_on_writer_thread(
        self, tmp_path, monkeypatch
    ):
        from bsseqconsensusreads_tpu.pipeline.extsort import (
            external_sort_raw,
        )
        from bsseqconsensusreads_tpu.utils.observe import Metrics

        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "1")
        monkeypatch.setenv("BSSEQ_TPU_RETRY_BACKOFF_S", "0.01")
        header, blobs = self._sorted_blobs(seed=7)
        failpoints.arm("extsort_spill=io_error:times=1")
        metrics = Metrics()
        got = list(external_sort_raw(
            iter(blobs), header, workdir=str(tmp_path), buffer_records=64,
            metrics=metrics,
        ))
        failpoints.disarm()
        monkeypatch.setenv("BSSEQ_TPU_HOST_WORKERS", "0")
        want = list(external_sort_raw(
            iter(blobs), header, workdir=str(tmp_path), buffer_records=64,
        ))
        assert got == want
        assert metrics.counters.get("batches_retried", 0) >= 1


@pytest.mark.slow
class TestScalingSmoke:
    def test_two_workers_beat_serial_on_cpu_bound_synthetic(self):
        """2-way host-scaling smoke: a GIL-releasing CPU-bound workload
        (BLAS matmuls, the shape of the native emit/rawize passes) must
        finish faster through a 2-worker HostPool than serially. Needs
        real cores — skipped on single-core builders."""
        import time

        if (os.cpu_count() or 1) < 2:
            pytest.skip("needs >=2 cores for host-parallel speedup")
        rng = np.random.default_rng(0)
        mats = [rng.standard_normal((700, 700)) for _ in range(2)]

        def work(_i):
            out = mats[0]
            for _ in range(4):
                out = out @ mats[1]
            return float(out[0, 0])

        n_tasks = 8
        work(0)  # warm BLAS
        t0 = time.monotonic()
        serial = [work(i) for i in range(n_tasks)]
        serial_s = time.monotonic() - t0

        pool = hostpool.HostPool(2)
        try:
            t0 = time.monotonic()
            futs = [pool.submit(work, i) for i in range(n_tasks)]
            parallel = [f.result() for f in futs]
            parallel_s = time.monotonic() - t0
        finally:
            pool.shutdown()
        assert parallel == serial
        assert parallel_s < serial_s, (parallel_s, serial_s)
