"""graftcontract: registry round-trip, seeded drift, waivers, tier-1 gate.

The whole-program pass (`analysis/contracts.py`) cross-references the
declared-surface registry against every extracted use in the package
AST. These tests pin three things:

* the registry itself is well-formed and agrees with its in-code
  mirrors (ledger_tools.EVENT_SCHEMA, faults.failpoints.SITES);
* each drift class actually fires — a scratch copy of the package with
  one seeded mutation (renamed event emit, undeclared env read,
  unknown protocol op, undeclared CLI flag) goes from clean to dirty,
  and an in-process registry mutation (deleted entry, orphan entry)
  does the same, so the gate catches drift at introduction in either
  direction;
* waiver semantics — mandatory why, stale-waiver hard error — and the
  tier-1 gate shelling `cli lint --contracts --json` over the package.

Scratch copies verify without README/fixture siblings, so doc and
fixture-wiring checks stay out of the mutation tests' way.
"""

import dataclasses
import json
import os
import re
import shutil
import subprocess
import sys

import pytest

from bsseqconsensusreads_tpu.analysis import contracts
from bsseqconsensusreads_tpu.analysis.engine import LintError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG_DIR = contracts.package_root()


def _verify_scratch(tmp_path, mutate=None, registry=None):
    """Copy the package into tmp_path, optionally mutate one file via
    `mutate(scratch_pkg_dir)`, and run the whole-program pass on it."""
    scratch = str(tmp_path / "bsseqconsensusreads_tpu")
    shutil.copytree(
        PKG_DIR, scratch,
        ignore=shutil.ignore_patterns("__pycache__", "*.pyc", "*.so"),
    )
    if mutate is not None:
        mutate(scratch)
    return contracts.verify_package([scratch], registry=registry)


def _rewrite(pkg, rel, old, new):
    path = os.path.join(pkg, rel)
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    assert old in text, f"mutation anchor missing from {rel}: {old!r}"
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(text.replace(old, new))


# ---------------------------------------------------------------------------
# registry round-trip


def test_registry_wellformed():
    reg = contracts.REGISTRY
    env_re = re.compile(r"^BSSEQ_TPU_[A-Z0-9_]+$")
    names = [v.name for v in reg.env_vars]
    assert len(names) == len(set(names))
    for v in reg.env_vars:
        assert env_re.match(v.name), v.name
        assert v.kind and v.owner and v.doc
    ev_names = [e.name for e in reg.events]
    assert len(ev_names) == len(set(ev_names))
    for e in reg.events:
        assert isinstance(e.fields, tuple)
        assert all(isinstance(f, str) for f in e.fields)
    for op in reg.ops:
        assert set(op.planes) <= {"serve", "router", "coordinator"}, op
        assert op.doc
    for w in reg.waivers:
        assert w.why.strip(), w.surface


def test_registry_mirrors_event_schema():
    # field tuples must agree verbatim — this is the emitter/consumer
    # contract the pass exists to hold
    from bsseqconsensusreads_tpu.utils.ledger_tools import EVENT_SCHEMA

    assert contracts.REGISTRY.event_fields() == {
        k: tuple(v) for k, v in EVENT_SCHEMA.items()
    }


def test_registry_mirrors_failpoint_sites():
    from bsseqconsensusreads_tpu.faults.failpoints import SITES

    assert contracts.REGISTRY.failpoint_sites == frozenset(SITES)


def test_report_roundtrips_through_json():
    report = contracts.verify_package()
    d = json.loads(json.dumps(report.as_dict()))
    assert d["ok"] is True
    assert d["drift"] == []
    assert d["checked"]["rules"] == len(contracts.REGISTRY.rules)
    assert any(w["surface"] == "op:fleet" and w["why"] for w in d["waived"])


def test_env_table_covers_registry():
    table = contracts.render_env_table()
    for v in contracts.REGISTRY.env_vars:
        assert f"`{v.name}`" in table


# ---------------------------------------------------------------------------
# seeded drift: scratch-copy package mutations


def test_scratch_copy_is_clean(tmp_path):
    report = _verify_scratch(tmp_path)
    assert report.ok, [d.format() for d in report.drifts]


def test_renamed_event_emit_drifts(tmp_path):
    report = _verify_scratch(tmp_path, mutate=lambda pkg: _rewrite(
        pkg, os.path.join("pipeline", "bucketemit.py"),
        '"bucket_plan",', '"bucket_plan_v2",',
    ))
    assert not report.ok
    kinds = {(d.kind, d.surface) for d in report.drifts}
    # new name is undeclared; old name is now declared-but-never-emitted
    assert ("undeclared", "event:bucket_plan_v2") in kinds
    assert ("unemitted", "event:bucket_plan") in kinds


def test_undeclared_env_read_drifts(tmp_path):
    report = _verify_scratch(tmp_path, mutate=lambda pkg: _rewrite(
        pkg, "config.py", "import os",
        'import os\n_GHOST = os.environ.get("BSSEQ_TPU_GHOST_KNOB")',
    ))
    assert not report.ok
    assert ("undeclared", "env:BSSEQ_TPU_GHOST_KNOB") in {
        (d.kind, d.surface) for d in report.drifts
    }


def test_unknown_protocol_op_drifts(tmp_path):
    report = _verify_scratch(tmp_path, mutate=lambda pkg: _rewrite(
        pkg, "config.py", "import os",
        'import os\n_GHOST_REQ = {"op": "frobnicate"}',
    ))
    assert not report.ok
    assert ("undeclared", "op:frobnicate") in {
        (d.kind, d.surface) for d in report.drifts
    }


def test_undeclared_cli_flag_drifts(tmp_path):
    report = _verify_scratch(tmp_path, mutate=lambda pkg: _rewrite(
        pkg, "cli.py", '"--list-rules", action="store_true"',
        '"--ghost-flag", action="store_true")\n'
        '    p.add_argument("--list-rules", action="store_true"',
    ))
    assert not report.ok
    assert ("undeclared", "cli:--ghost-flag") in {
        (d.kind, d.surface) for d in report.drifts
    }


def test_undeclared_fire_site_drifts(tmp_path):
    report = _verify_scratch(tmp_path, mutate=lambda pkg: _rewrite(
        pkg, os.path.join("pipeline", "bucketemit.py"),
        '_failpoints.fire("bucket_spill", bucket=bucket, run=run_index)',
        '_failpoints.fire("ghost_site", bucket=bucket, run=run_index)',
    ))
    assert not report.ok
    assert ("undeclared", "failpoint:ghost_site") in {
        (d.kind, d.surface) for d in report.drifts
    }


# ---------------------------------------------------------------------------
# seeded drift: registry mutations over the real package


def test_deleted_event_entry_drifts():
    reg = contracts.REGISTRY
    pruned = dataclasses.replace(
        reg, events=tuple(e for e in reg.events if e.name != "spill"),
    )
    report = contracts.verify_package(registry=pruned)
    assert not report.ok
    kinds = {(d.kind, d.surface) for d in report.drifts}
    assert ("undeclared", "event:spill") in kinds
    assert ("mismatch", "event:spill") in kinds  # EVENT_SCHEMA still has it


def test_deleted_env_entry_drifts():
    reg = contracts.REGISTRY
    pruned = dataclasses.replace(
        reg,
        env_vars=tuple(v for v in reg.env_vars
                       if v.name != "BSSEQ_TPU_STATS"),
    )
    report = contracts.verify_package(registry=pruned)
    assert not report.ok
    assert ("undeclared", "env:BSSEQ_TPU_STATS") in {
        (d.kind, d.surface) for d in report.drifts
    }


def test_orphan_event_entry_drifts():
    reg = contracts.REGISTRY
    padded = dataclasses.replace(
        reg,
        events=reg.events + (
            contracts.LedgerEvent("ghost_event", ("what",), "nowhere"),
        ),
    )
    report = contracts.verify_package(registry=padded)
    assert not report.ok
    kinds = {(d.kind, d.surface) for d in report.drifts}
    assert ("unemitted", "event:ghost_event") in kinds
    assert ("unconsumed", "event:ghost_event") in kinds


def test_missing_fixture_is_unwired():
    reg = contracts.REGISTRY
    padded = dataclasses.replace(
        reg, rules=reg.rules | {"ghost-rule"},
    )
    report = contracts.verify_package(registry=padded)
    assert not report.ok
    kinds = {(d.kind, d.surface) for d in report.drifts}
    # half-landed rule: no Rule() definition, no seeded fixture, no docs
    assert ("unwired", "rule:ghost-rule") in kinds
    assert ("unused", "rule:ghost-rule") in kinds


# ---------------------------------------------------------------------------
# waiver semantics


def test_waiver_without_why_is_hard_error():
    reg = contracts.REGISTRY
    bad = dataclasses.replace(
        reg, waivers=reg.waivers + (
            contracts.Waiver("unused", "op:fleet2", "  "),
        ),
    )
    with pytest.raises(LintError, match="no why"):
        contracts.verify_package(registry=bad)


def test_stale_waiver_is_hard_error():
    reg = contracts.REGISTRY
    stale = dataclasses.replace(
        reg, waivers=reg.waivers + (
            contracts.Waiver("unused", "env:BSSEQ_TPU_NOT_A_DRIFT",
                             "excuses nothing"),
        ),
    )
    with pytest.raises(LintError, match="stale contract waiver"):
        contracts.verify_package(registry=stale)


def test_waiver_suppresses_matching_drift():
    reg = contracts.REGISTRY
    pruned = dataclasses.replace(
        reg,
        events=tuple(e for e in reg.events if e.name != "spill"),
        waivers=reg.waivers + (
            contracts.Waiver("undeclared", "event:spill", "test waiver"),
            contracts.Waiver("mismatch", "event:spill", "test waiver"),
        ),
    )
    report = contracts.verify_package(registry=pruned)
    assert report.ok, [d.format() for d in report.drifts]
    assert sum(n for _, n in report.waived) >= 3  # op:fleet + the two


# ---------------------------------------------------------------------------
# tier-1 gate: self-application through the CLI


def test_cli_contracts_gate():
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "bsseqconsensusreads_tpu.cli",
         "lint", "--contracts", "--json"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["ok"] is True
    assert out["drift"] == []
    for w in out["waived"]:
        assert w["why"].strip()
        assert w["matched"] >= 1
