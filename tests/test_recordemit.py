"""Native batch record emitter vs the Python emit + encode path.

The C++ emitter (native/wirepack.cpp wirepack_emit_consensus_records) must
produce byte-for-byte the records that pipeline.calling's Python emitters
build and io.bam.encode_record serializes — it is a pure speed
substitution for the per-record hot path, so any divergence is silent
output corruption. Each case runs both paths over randomized kernel-output
batches (gappy coverage, empty roles, min_reads skips, missing RX, both
alignment modes, molecular and duplex tag surfaces) and diffs the blobs.
"""

from __future__ import annotations

import numpy as np
import pytest

from bsseqconsensusreads_tpu.io import wirepack
from bsseqconsensusreads_tpu.io.bam import encode_record
from bsseqconsensusreads_tpu.models.params import ConsensusParams


pytestmark = pytest.mark.skipif(
    not wirepack.available(), reason=f"native wirepack: {wirepack.load_error()}"
)


class _Meta:
    def __init__(self, mi, rx, ref_id, window_start, role_reverse, n_templates):
        self.mi = mi
        self.rx = rx
        self.ref_id = ref_id
        self.window_start = window_start
        self.role_reverse = role_reverse
        self.n_templates = n_templates


class _Batch:
    def __init__(self, meta, bases):
        self.meta = meta
        self.bases = bases


def _random_outputs(f, w, seed, duplex, deep=False):
    rng = np.random.default_rng(seed)
    cover = rng.random((f, 2, w)) < 0.6
    # gappy interior coverage + some all-empty roles
    cover[rng.random(f) < 0.15, rng.integers(0, 2, size=f)[0]] = False
    maxd = 900 if deep else 3
    depth = np.where(cover, rng.integers(1, maxd + 1, size=(f, 2, w)), 0).astype(
        np.int16
    )
    errors = np.minimum(
        rng.integers(0, 3, size=(f, 2, w)), depth
    ).astype(np.int16)
    out = {
        "base": np.where(cover, rng.integers(0, 4, size=(f, 2, w)), 4).astype(
            np.int8
        ),
        "qual": np.where(cover, rng.integers(2, 94, size=(f, 2, w)), 0).astype(
            np.uint8
        ),
        "depth": depth,
        "errors": errors,
    }
    if duplex:
        a = np.where(cover, rng.integers(0, 2, size=(f, 2, w)), 0).astype(np.int8)
        out["a_depth"] = a
        out["b_depth"] = np.where(depth > 0, np.minimum(depth, 2) - a, 0).astype(
            np.int8
        )
    return out


def _metas(f, seed, with_rx=True):
    rng = np.random.default_rng(seed + 1)
    metas = []
    for i in range(f):
        metas.append(
            _Meta(
                mi=f"{i}/{'AB'[i % 2]}" if i % 3 else str(i),
                rx="ACGT-TGCA" if (with_rx and i % 4) else "",
                ref_id=int(rng.integers(0, 3)),
                window_start=int(rng.integers(0, 5000)),
                role_reverse=(bool(i % 2), not bool(i % 2)),
                n_templates=int(rng.integers(0, 6)),
            )
        )
    return metas


def _python_blob(batch, out, params, mode, duplex):
    from bsseqconsensusreads_tpu.pipeline.calling import (
        StageStats,
        _emit_duplex_batch,
        _emit_molecular_batch,
    )

    stats = StageStats()
    emit = _emit_duplex_batch if duplex else _emit_molecular_batch
    records = emit(batch, out, params, mode, stats)
    return (
        b"".join(encode_record(r) for r in records),
        len(records),
        stats.skipped_families,
    )


def _native_blob(batch, out, params, mode, duplex):
    if duplex:
        n_reads = np.array([m.n_templates for m in batch.meta], np.int32)
        role_reverse = np.tile(
            np.array([0, 1], np.uint8), (len(batch.meta), 1)
        )
    else:
        n_reads = (
            (batch.bases != 4).any(axis=-1).sum(axis=(-2, -1)).astype(np.int32)
        )
        role_reverse = np.array(
            [[int(m.role_reverse[0]), int(m.role_reverse[1])] for m in batch.meta],
            np.uint8,
        )
    return wirepack.emit_consensus_records(
        out,
        ref_id=[m.ref_id for m in batch.meta],
        window_start=[m.window_start for m in batch.meta],
        n_reads=n_reads,
        role_reverse=role_reverse,
        mi=[m.mi for m in batch.meta],
        rx=[m.rx for m in batch.meta],
        min_reads=params.min_reads,
        mode_self=(mode == "self"),
        duplex=duplex,
    )


@pytest.mark.parametrize("duplex", [False, True])
@pytest.mark.parametrize("mode", ["unaligned", "self"])
@pytest.mark.parametrize("seed", [0, 1])
def test_native_emit_matches_python(duplex, mode, seed):
    f, w = 23, 40
    out = _random_outputs(f, w, seed, duplex)
    metas = _metas(f, seed)
    if duplex:
        bases = None
        batch = _Batch(metas, np.zeros((f, 1, 2, w), np.int8))
        params = ConsensusParams(min_reads=2)  # exercises n_templates skips
    else:
        rng = np.random.default_rng(seed + 2)
        bases = np.where(
            rng.random((f, 4, 2, w)) < 0.7, rng.integers(0, 4, (f, 4, 2, w)), 4
        ).astype(np.int8)
        # some families fall below min_reads
        bases[rng.random(f) < 0.2] = 4
        batch = _Batch(metas, bases)
        params = ConsensusParams(min_reads=3)
    want, want_n, want_skip = _python_blob(batch, out, params, mode, duplex)
    got, got_n, got_skip = _native_blob(batch, out, params, mode, duplex)
    assert (got_n, got_skip) == (want_n, want_skip)
    assert got == want


def test_native_emit_deep_depths_and_no_rx():
    # depths past int8/uint8 exercise the u16 cd/ce packing; rx="" drops RX
    f, w = 9, 32
    out = _random_outputs(f, w, 5, duplex=False, deep=True)
    metas = _metas(f, 5, with_rx=False)
    batch = _Batch(metas, np.zeros((f, 2, 2, w), np.int8) + 1)
    params = ConsensusParams(min_reads=0)
    want, want_n, _ = _python_blob(batch, out, params, "self", False)
    got, got_n, _ = _native_blob(batch, out, params, "self", False)
    assert got_n == want_n and got == want


def test_native_emit_roundtrips_through_reader(tmp_path):
    # the blob must parse back as valid records via the first-party reader
    import gzip

    from bsseqconsensusreads_tpu.io.bam import BamHeader, BamReader, BamWriter

    f, w = 7, 24
    out = _random_outputs(f, w, 9, duplex=True)
    metas = _metas(f, 9)
    batch = _Batch(metas, np.zeros((f, 1, 2, w), np.int8))
    params = ConsensusParams(min_reads=0)
    blob, n, _ = _native_blob(batch, out, params, "unaligned", True)
    path = str(tmp_path / "raw.bam")
    header = BamHeader("@HD\tVN:1.6\n", [("chr1", 10000)])
    with BamWriter(path, header) as wtr:
        wtr.write_raw(blob)
    with gzip.open(path, "rb") as fh:
        assert fh.read(4) == b"BAM\x01"
    with BamReader(path) as rdr:
        recs = list(rdr)
    assert len(recs) == n
    for r in recs:
        assert r.has_tag("MI") and r.has_tag("cd") and r.has_tag("ad")
        assert len(r.seq) == len(r.qual)


class TestEmitIntegration:
    """emit='native' through the real batch callers + writers must produce
    the same BAM as emit='python', including via checkpoint shards."""

    def _duplex_inputs(self, tmp_path):
        from bsseqconsensusreads_tpu.utils.testing import (
            make_aligned_duplex_group,
            random_genome,
        )

        rng = np.random.default_rng(21)
        name, genome = random_genome(rng, 4000)
        records = []
        for fam in range(17):
            records.extend(
                make_aligned_duplex_group(
                    rng, name, genome, mi=fam,
                    start=int(rng.integers(0, 3500)), length=70,
                )
            )
        return name, genome, records

    def test_duplex_native_vs_python_bam(self, tmp_path):
        from bsseqconsensusreads_tpu.io.bam import BamHeader, BamReader, BamWriter
        from bsseqconsensusreads_tpu.io.fasta import FastaFile
        from bsseqconsensusreads_tpu.pipeline.calling import (
            StageStats,
            call_duplex_batches,
        )
        from bsseqconsensusreads_tpu.utils.testing import write_fasta

        name, genome, records = self._duplex_inputs(tmp_path)
        fa = str(tmp_path / "g.fa")
        write_fasta(fa, name, genome)
        fasta = FastaFile(fa)
        header = BamHeader("@HD\tVN:1.6\n", [(name, len(genome))])
        paths = {}
        stats_by = {}
        for emit in ("python", "native"):
            stats = StageStats()
            path = str(tmp_path / f"{emit}.bam")
            with BamWriter(path, header) as w:
                for batch in call_duplex_batches(
                    iter(records), fasta.fetch, [name], stats=stats,
                    batch_families=5, emit=emit,
                ):
                    from bsseqconsensusreads_tpu.io.bam import write_items

                    write_items(w, batch)
            paths[emit] = path
            stats_by[emit] = stats
        assert (
            stats_by["native"].consensus_out
            == stats_by["python"].consensus_out
            > 0
        )
        with BamReader(paths["python"]) as a, BamReader(paths["native"]) as b:
            rec_a = list(a.raw_records())
            rec_b = list(b.raw_records())
        assert rec_a == rec_b

    def test_molecular_native_through_checkpoint(self, tmp_path):
        from bsseqconsensusreads_tpu.io.bam import BamReader
        from bsseqconsensusreads_tpu.pipeline.calling import (
            StageStats,
            call_molecular_batches,
        )
        from bsseqconsensusreads_tpu.pipeline.checkpoint import BatchCheckpoint
        from bsseqconsensusreads_tpu.utils.testing import (
            make_grouped_bam_records,
            random_genome,
        )

        rng = np.random.default_rng(33)
        name, genome = random_genome(rng, 6000)
        header, records = make_grouped_bam_records(
            rng, name, genome, n_families=9
        )
        outs = {}
        for emit in ("python", "native"):
            target = str(tmp_path / f"mol_{emit}.bam")
            ck = BatchCheckpoint(target, header, every=2)
            batches = call_molecular_batches(
                iter(records), batch_families=3, emit=emit,
                stats=StageStats(),
            )
            ck.write_batches(batches)
            ck.finalize()
            with BamReader(target) as r:
                outs[emit] = list(r.raw_records())
        assert outs["python"] == outs["native"] and len(outs["python"]) > 0


def test_native_emit_rejects_overlong_qname():
    # BAM l_read_name is uint8: the Python encoder raises struct.error for
    # a 255+ char qname; the native emitter must refuse too, not truncate
    f, w = 2, 16
    out = _random_outputs(f, w, 13, duplex=False)
    metas = _metas(f, 13)
    metas[1].mi = "M" * 300
    batch = _Batch(metas, np.ones((f, 2, 2, w), np.int8))
    with pytest.raises(ValueError, match="254"):
        _native_blob(batch, out, ConsensusParams(min_reads=0), "self", False)


def test_self_mode_native_pipeline_matches_python(tmp_path):
    """Full self-aligned run_pipeline with emit native vs python: the final
    coordinate-sorted BAMs must be byte-identical (native emit + raw-blob
    external sort vs object emit + object sort)."""
    import numpy as np

    from bsseqconsensusreads_tpu.config import FrameworkConfig
    from bsseqconsensusreads_tpu.io.bam import BamWriter
    from bsseqconsensusreads_tpu.pipeline.stages import run_pipeline
    from bsseqconsensusreads_tpu.utils.testing import (
        make_grouped_bam_records,
        random_genome,
        write_fasta,
    )

    rng = np.random.default_rng(51)
    name, genome = random_genome(rng, 8000)
    header, records = make_grouped_bam_records(rng, name, genome, n_families=10)
    inbam = str(tmp_path / "in.bam")
    with BamWriter(inbam, header) as w:
        for r in records:
            w.write(r)
    fa = str(tmp_path / "g.fa")
    write_fasta(fa, name, genome)
    outs = {}
    for emit in ("python", "native"):
        cfg = FrameworkConfig(
            genome_dir=str(tmp_path), genome_fasta_file_name="g.fa",
            aligner="self", emit=emit,
        )
        outdir = str(tmp_path / f"out_{emit}")
        target, _, _ = run_pipeline(cfg, inbam, outdir=outdir)
        outs[emit] = open(target, "rb").read()
    assert outs["python"] == outs["native"] and len(outs["python"]) > 100


def test_deep_family_batched_native_emit_matches_python(tmp_path):
    """Deep families (over deep_threshold) dispatch batched per template
    bucket and emit through the native path: the written BAM must be
    byte-identical to emit='python', and same-bucket families must share
    one kernel batch (round-2 VERDICT item 6)."""
    import numpy as np

    from bsseqconsensusreads_tpu.io.bam import BamRecord, BamHeader, BamWriter, CMATCH
    from bsseqconsensusreads_tpu.pipeline.calling import (
        StageStats,
        call_molecular_batches,
    )
    from bsseqconsensusreads_tpu.utils.testing import random_genome

    rng = np.random.default_rng(123)
    name, genome = random_genome(rng, 600)

    def family(mi, depth, start):
        recs = []
        for d in range(depth):
            for flag, pos in ((99, start), (147, start + 60)):
                r = BamRecord(
                    qname=f"m{mi}t{d}", flag=flag, ref_id=0, pos=pos, mapq=60,
                    cigar=[(CMATCH, 40)], next_ref_id=0,
                    next_pos=start + 60 if flag == 99 else start,
                    seq=genome[pos : pos + 40], qual=bytes([30] * 40),
                )
                r.set_tag("MI", f"{mi}/A", "Z")
                r.set_tag("RX", "AC-GT", "Z")
                recs.append(r)
        return recs

    # two deep families landing in the SAME template bucket (17, 20 -> 32),
    # one in another (40 -> 64), one normal family (4)
    records = (
        family(0, 17, 50) + family(1, 20, 150) + family(2, 40, 250)
        + family(3, 4, 350)
    )
    outs, stats_by = {}, {}
    for emit in ("python", "native"):
        stats = StageStats()
        batches = list(
            call_molecular_batches(
                iter(records), mode="self", grouping="adjacent", stats=stats,
                mesh=None, deep_threshold=16, emit=emit,
            )
        )
        path = str(tmp_path / f"deep_{emit}.bam")
        header = BamHeader("@HD\tVN:1.6\n", [(name, len(genome))])
        from bsseqconsensusreads_tpu.io.bam import write_items

        with BamWriter(path, header, engine="python") as w:
            n = sum(write_items(w, b) for b in batches)
        assert n == 8  # 4 families x R1+R2
        outs[emit] = open(path, "rb").read()
        stats_by[emit] = stats
    assert outs["python"] == outs["native"]
    for stats in stats_by.values():
        assert stats.families == 4 and stats.skipped_families == 0
        # 1 normal batch + 2 deep bucket batches (17&20 share bucket 32)
        assert stats.batches == 3
