"""Overlap pipeline (pipeline.calling worker-thread dispatch/fetch): output
must be byte-identical to inline dispatch, batch order preserved (the
checkpoint skip_batches contract), and the pool must wind down cleanly on
early generator close.

The overlap engine exists for the tunneled-TPU production path (round-4
scale artifact: kernel+fetch serialized against host work); on the CPU
test backend it is off by default, so these tests force it via
BSSEQ_TPU_OVERLAP_THREADS and assert pure equivalence.
"""

from __future__ import annotations

import numpy as np
import pytest

from bsseqconsensusreads_tpu.io.bam import BamWriter, write_items
from bsseqconsensusreads_tpu.models.params import ConsensusParams
from bsseqconsensusreads_tpu.pipeline.calling import (
    StageStats,
    call_duplex_batches,
    call_molecular_batches,
)
from bsseqconsensusreads_tpu.utils.testing import (
    make_aligned_duplex_group,
    make_grouped_bam_records,
    random_genome,
)


@pytest.fixture(scope="module")
def molecular_corpus():
    rng = np.random.default_rng(23)
    name, genome = random_genome(rng, 14000)
    # reads_per_strand from 1 exercises the T==1 singleton host-vote path
    # (worker-side in overlap mode) alongside normal kernel batches
    header, records = make_grouped_bam_records(
        rng, name, genome, n_families=24, reads_per_strand=(1, 3)
    )
    return header, records


@pytest.fixture(scope="module")
def duplex_corpus():
    rng = np.random.default_rng(29)
    name, genome = random_genome(rng, 16000)
    records = []
    for fam in range(30):
        records.extend(
            make_aligned_duplex_group(
                rng, name, genome, fam, 60 + fam * 120, 70,
                softclip=2 if fam % 4 == 0 else 0,
            )
        )
    records.sort(key=lambda r: (r.ref_id, r.pos))
    return name, genome, records


def _mol_bytes(records, header, tmp_path, tag, transport="auto"):
    stats = StageStats()
    out = str(tmp_path / f"mol_{tag}.bam")
    batches = call_molecular_batches(
        iter(list(records)), params=ConsensusParams(min_reads=1),
        mode="self", batch_families=7, grouping="coordinate",
        stats=stats, mesh=None, transport=transport,
    )
    with BamWriter(out, header, engine="python") as w:
        for b in batches:
            write_items(w, b)
    return open(out, "rb").read(), stats


def _dup_bytes(corpus, tmp_path, tag):
    name, genome, records = corpus
    stats = StageStats()
    out = str(tmp_path / f"dup_{tag}.bam")
    batches = call_duplex_batches(
        iter(list(records)), lambda n, s, e: genome[s:e], [name],
        mode="self", batch_families=8, grouping="coordinate",
        stats=stats, mesh=None,
    )
    from bsseqconsensusreads_tpu.io.bam import BamHeader

    header = BamHeader("@HD\tVN:1.6\tSO:coordinate\n", [(name, len(genome))])
    with BamWriter(out, header, engine="python") as w:
        for b in batches:
            write_items(w, b)
    return open(out, "rb").read(), stats


class TestOverlapEquivalence:
    def test_molecular_overlap_matches_inline(
        self, molecular_corpus, tmp_path, monkeypatch
    ):
        header, records = molecular_corpus
        monkeypatch.setenv("BSSEQ_TPU_OVERLAP_THREADS", "0")
        inline, st0 = _mol_bytes(records, header, tmp_path, "inline")
        monkeypatch.setenv("BSSEQ_TPU_OVERLAP_THREADS", "2")
        overlap, st2 = _mol_bytes(records, header, tmp_path, "overlap")
        assert overlap == inline and len(inline) > 200
        assert st2.batches == st0.batches
        assert st2.consensus_out == st0.consensus_out
        # worker-side phases accounted; main-thread stall visible
        assert "stall" in st2.metrics.seconds
        assert "stall" not in st0.metrics.seconds

    def test_molecular_overlap_matches_inline_wire(
        self, molecular_corpus, tmp_path, monkeypatch
    ):
        """Explicit wire transport: worker-side H2D pack + slim fetch +
        exact count recompute must match the inline wire run."""
        header, records = molecular_corpus
        monkeypatch.setenv("BSSEQ_TPU_OVERLAP_THREADS", "0")
        inline, _ = _mol_bytes(records, header, tmp_path, "inw", "wire")
        monkeypatch.setenv("BSSEQ_TPU_OVERLAP_THREADS", "3")
        overlap, _ = _mol_bytes(records, header, tmp_path, "ovw", "wire")
        assert overlap == inline

    def test_duplex_overlap_matches_inline(
        self, duplex_corpus, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("BSSEQ_TPU_OVERLAP_THREADS", "0")
        inline, st0 = _dup_bytes(duplex_corpus, tmp_path, "inline")
        monkeypatch.setenv("BSSEQ_TPU_OVERLAP_THREADS", "2")
        overlap, st2 = _dup_bytes(duplex_corpus, tmp_path, "overlap")
        assert overlap == inline and len(inline) > 200
        assert st2.consensus_out == st0.consensus_out
        assert "stall" in st2.metrics.seconds

    def test_early_close_shuts_pool_down(self, duplex_corpus, monkeypatch):
        """Closing the batch generator mid-stream (a consumer break) must
        not hang on in-flight workers or leak the executor."""
        import threading

        name, genome, records = duplex_corpus
        monkeypatch.setenv("BSSEQ_TPU_OVERLAP_THREADS", "2")
        before = {t.name for t in threading.enumerate()}
        batches = call_duplex_batches(
            iter(list(records)), lambda n, s, e: genome[s:e], [name],
            mode="self", batch_families=5, grouping="coordinate",
            stats=StageStats(), mesh=None,
        )
        next(batches)
        batches.close()
        leaked = {
            t.name
            for t in threading.enumerate()
            if t.name.startswith("bsseq-ovl") and t.is_alive()
        } - before
        assert not leaked
