"""End-to-end pipeline tests: record ops, workflow engine, streaming callers,
the self-aligned full pipeline, and the CLI."""

import gzip
import json
import os

import numpy as np
import pytest

from bsseqconsensusreads_tpu.config import FrameworkConfig
from bsseqconsensusreads_tpu.io.bam import BamHeader, BamReader, BamRecord, BamWriter, CMATCH
from bsseqconsensusreads_tpu.io.sam import format_sam_record, parse_sam_line, read_sam
from bsseqconsensusreads_tpu.pipeline.calling import StageStats, call_duplex, call_molecular
from bsseqconsensusreads_tpu.pipeline.record_ops import (
    coordinate_key,
    coordinate_sort,
    filter_mapped,
    name_sort,
    template_coordinate_sort,
    zipper_bams,
)
from bsseqconsensusreads_tpu.pipeline.stages import run_pipeline, sample_name
from bsseqconsensusreads_tpu.pipeline.workflow import Workflow, WorkflowError
from bsseqconsensusreads_tpu.utils.testing import (
    bisulfite_convert,
    make_grouped_bam_records,
    random_genome,
    write_fasta,
)


def rec(qname, flag, pos=0, ref_id=0, **kw):
    r = BamRecord(qname=qname, flag=flag, ref_id=ref_id, pos=pos,
                  seq=kw.pop("seq", "ACGT"), qual=kw.pop("qual", bytes([30] * 4)),
                  cigar=kw.pop("cigar", [(CMATCH, 4)]), **kw)
    return r


class TestRecordOps:
    def test_filter_mapped(self):
        recs = [rec("a", 0), rec("b", 4), rec("c", 99)]
        assert [r.qname for r in filter_mapped(recs)] == ["a", "c"]

    def test_sorts(self):
        recs = [rec("b", 99, pos=50), rec("a", 147, pos=10), rec("a", 99, pos=5)]
        assert [r.qname for r in name_sort(recs)] == ["a", "a", "b"]
        assert [r.pos for r in coordinate_sort(recs)] == [5, 10, 50]

    def test_template_coordinate_groups_duplex_mates(self):
        # A/B strand reads of one MI must become adjacent despite positions.
        a1 = rec("x", 99, pos=100)
        a1.set_tag("MI", "7/A", "Z")
        other = rec("y", 99, pos=105)
        other.set_tag("MI", "9/A", "Z")
        b1 = rec("z", 163, pos=100)
        b1.set_tag("MI", "7/B", "Z")
        srt = template_coordinate_sort([other, b1, a1])
        mis = [str(r.get_tag("MI")).split("/")[0] for r in srt]
        assert mis == ["7", "7", "9"]

    def test_zipper_grafts_tags(self):
        aligned = rec("q1", 99, pos=10)
        unaligned = rec("q1", 77)
        unaligned.set_tag("MI", "5/A", "Z")
        unaligned.set_tag("RX", "AAAA-TTTT", "Z")
        unaligned.set_tag("cD", 7, "i")
        out = zipper_bams([aligned], [unaligned])
        assert out[0].get_tag("MI") == "5/A"
        assert out[0].get_tag("cD") == 7
        # aligned record without partner passes through
        lone = rec("solo", 99, pos=5)
        assert zipper_bams([lone], [unaligned])[0].qname == "solo"


class TestSamInterop:
    def test_sam_round_trip(self):
        header = BamHeader("@HD\tVN:1.6\n", [("chr1", 1000)])
        r = rec("q", 99, pos=42, seq="ACGTA", qual=bytes([30, 31, 32, 33, 34]),
                cigar=[(CMATCH, 5)], next_ref_id=0, next_pos=100, tlen=62)
        r.set_tag("MI", "3/A", "Z")
        r.set_tag("cD", 4, "i")
        r.set_tag("cd", ("S", [1, 2, 3]), "B")
        line = format_sam_record(r, header)
        back = parse_sam_line(line, header)
        assert back.qname == "q" and back.pos == 42 and back.seq == "ACGTA"
        assert back.qual == r.qual
        assert back.get_tag("MI") == "3/A"
        assert back.get_tag("cd") == ("S", [1, 2, 3])

    def test_read_sam_stream(self):
        import io as _io

        text = (
            "@HD\tVN:1.6\n@SQ\tSN:c\tLN:100\n"
            "q\t99\tc\t11\t60\t4M\t=\t20\t13\tACGT\tIIII\tMI:Z:1/A\n"
        )
        header, records = read_sam(_io.StringIO(text))
        recs = list(records)
        assert header.references == [("c", 100)]
        assert recs[0].pos == 10
        assert recs[0].get_tag("MI") == "1/A"


class TestWorkflowEngine:
    def test_dag_run_skip_and_rerun(self, tmp_path):
        log = []
        src = tmp_path / "in.txt"
        mid = tmp_path / "mid.txt"
        out = tmp_path / "out.txt"
        src.write_text("1")

        def mk(name, inp, outp):
            def run(rule):
                log.append(name)
                outp.write_text(inp.read_text() + name)

            return run

        wf = Workflow()
        wf.rule("a", [str(src)], [str(mid)], mk("a", src, mid))
        wf.rule("b", [str(mid)], [str(out)], mk("b", mid, out))
        res = wf.run([str(out)])
        assert [r.name for r in res if r.ran] == ["a", "b"]
        # second run: everything up to date
        res = wf.run([str(out)])
        assert all(not r.ran for r in res)
        # touch the source: both rules re-run
        os.utime(src, (os.path.getmtime(src) + 10,) * 2)
        res = wf.run([str(out)])
        assert [r.name for r in res if r.ran] == ["a", "b"]

    def test_temp_cleanup(self, tmp_path):
        src = tmp_path / "in.txt"
        mid = tmp_path / "mid.txt"
        out = tmp_path / "out.txt"
        src.write_text("1")
        wf = Workflow()
        wf.rule("a", [str(src)], [str(mid)], lambda r: mid.write_text("m"),
                temp_outputs=[str(mid)])
        wf.rule("b", [str(mid)], [str(out)], lambda r: out.write_text("o"))
        wf.run([str(out)])
        assert out.exists() and not mid.exists()

    def test_missing_input_raises(self, tmp_path):
        wf = Workflow()
        wf.rule("a", [str(tmp_path / "ghost")], [str(tmp_path / "x")], lambda r: None)
        with pytest.raises(WorkflowError, match="no rule produces"):
            wf.run([str(tmp_path / "x")])

    def test_duplicate_output_rejected(self, tmp_path):
        wf = Workflow()
        wf.rule("a", [], [str(tmp_path / "x")], lambda r: None)
        with pytest.raises(WorkflowError, match="produced by both"):
            wf.rule("b", [], [str(tmp_path / "x")], lambda r: None)


@pytest.fixture(scope="module")
def pipeline_env(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("pipe")
    rng = np.random.default_rng(31)
    name, genome = random_genome(rng, 6000)
    fasta = str(tmp / "genome.fa")
    write_fasta(fasta, name, genome)
    header, records = make_grouped_bam_records(
        rng, name, genome, n_families=12, error_rate=0.01
    )
    bam = str(tmp / "input" / "sampleX.bam")
    os.makedirs(os.path.dirname(bam), exist_ok=True)
    with BamWriter(bam, header) as w:
        w.write_all(records)
    return {"tmp": tmp, "genome": genome, "name": name, "fasta": fasta, "bam": bam}


class TestSelfAlignedPipeline:
    def test_full_run(self, pipeline_env):
        env = pipeline_env
        cfg = FrameworkConfig(
            genome_dir=os.path.dirname(env["fasta"]),
            genome_fasta_file_name=os.path.basename(env["fasta"]),
            aligner="self",
        )
        outdir = str(env["tmp"] / "output")
        target, results, stats = run_pipeline(cfg, env["bam"], outdir=outdir)
        assert os.path.exists(target)
        assert [r.name for r in results if r.ran] == [
            "call_consensus_molecular_tpu",
            "call_duplex_tpu",
        ]
        with BamReader(target) as r:
            duplex = list(r)
        # 12 families -> R1+R2 each
        assert len(duplex) == 24
        genome = env["genome"]
        checked = 0
        for d in duplex:
            assert d.has_tag("MI") and d.has_tag("cD") and d.has_tag("cd")
            expect = bisulfite_convert(
                genome[d.pos : d.pos + len(d.seq)], genome, d.pos, "A"
            )
            mismatches = sum(a != b for a, b in zip(d.seq, expect))
            assert mismatches <= 2  # 1% raw error, depth>=4: near-perfect
            checked += 1
        assert checked == 24
        # second invocation: everything cached
        _, results2, _ = run_pipeline(cfg, env["bam"], outdir=outdir)
        assert all(not r.ran for r in results2)

    def test_intermediate_level_preserves_final_output(self, pipeline_env):
        """Intermediates deflate at cfg.intermediate_level (fast), the final
        target at the standard level — and the level of the intermediate
        must never change the final target's bytes (compression is
        transparent to content)."""
        env = pipeline_env
        outs = {}
        inter_sizes = {}
        for level in (1, 6):
            cfg = FrameworkConfig(
                genome_dir=os.path.dirname(env["fasta"]),
                genome_fasta_file_name=os.path.basename(env["fasta"]),
                aligner="self",
                intermediate_level=level,
            )
            outdir = str(env["tmp"] / f"out_lvl{level}")
            target, _, _ = run_pipeline(cfg, env["bam"], outdir=outdir)
            outs[level] = open(target, "rb").read()
            inter = os.path.join(
                outdir,
                "sampleX_consensus_unfiltered_aunamerged_aligned.bam",
            )
            inter_sizes[level] = os.path.getsize(inter)
        assert outs[1] == outs[6]
        # level 1 compresses no better than level 6 (equal only possible on
        # tiny inputs; sanity that the knob reached the writer)
        assert inter_sizes[1] >= inter_sizes[6]

    def test_stats_populated(self, pipeline_env):
        env = pipeline_env
        cfg = FrameworkConfig(
            genome_dir=os.path.dirname(env["fasta"]),
            genome_fasta_file_name=os.path.basename(env["fasta"]),
            aligner="self",
        )
        outdir = str(env["tmp"] / "output2")
        _, _, stats = run_pipeline(cfg, env["bam"], outdir=outdir)
        assert stats["molecular"].families == 24  # 12 MIs x 2 strands
        assert stats["duplex"].families == 12
        assert stats["molecular"].consensus_out == 48
        assert 0 <= stats["molecular"].pad_waste < 1


class TestParityModeStages:
    def test_unaligned_molecular_then_fastq(self, pipeline_env):
        env = pipeline_env
        cfg = FrameworkConfig(
            genome_dir=os.path.dirname(env["fasta"]),
            genome_fasta_file_name=os.path.basename(env["fasta"]),
            aligner="none",
        )
        outdir = str(env["tmp"] / "output3")
        target, results, _ = run_pipeline(cfg, env["bam"], outdir=outdir)
        assert target.endswith("_unalignedConsensus_unfiltered_1.fq.gz")
        sample = sample_name(env["bam"])
        mol = os.path.join(outdir, f"{sample}_unalignedConsensus_molecular.bam")
        with BamReader(mol) as r:
            recs = list(r)
        assert all(r.flag in (77, 141) for r in recs)
        assert all(r.ref_id == -1 and r.pos == -1 for r in recs)
        lines = gzip.open(target, "rt").read().splitlines()
        assert len(lines) == 4 * sum(1 for r in recs if r.flag == 77)

    def test_bwameth_missing_raises(self, pipeline_env):
        env = pipeline_env
        cfg = FrameworkConfig(
            genome_dir=os.path.dirname(env["fasta"]),
            genome_fasta_file_name=os.path.basename(env["fasta"]),
            aligner="bwameth",
        )
        outdir = str(env["tmp"] / "output4")
        with pytest.raises(WorkflowError, match="bwameth"):
            run_pipeline(cfg, env["bam"], outdir=outdir)

    def test_bwameth_stderr_logged(self, pipeline_env, tmp_path):
        """The reference tees the first alignment's bwameth stderr to
        output/log/bwameth_results/{sample}_consensus_unfiltered.log
        (main.snake.py:88-89) and declares no log on the final duplex
        alignment (:186-189); run_bwameth reproduces both."""
        from bsseqconsensusreads_tpu.pipeline.stages import PipelineBuilder
        from bsseqconsensusreads_tpu.pipeline.workflow import Rule

        env = pipeline_env
        fake = tmp_path / "fake_bwameth.sh"
        fake.write_text(
            "#!/bin/sh\n"
            "echo 'bwameth-parity-log-line' >&2\n"
            "printf '@HD\\tVN:1.6\\tSO:unsorted\\n'\n"
            "printf '@SQ\\tSN:chr1\\tLN:1000\\n'\n"
            "printf 'r1\\t0\\tchr1\\t1\\t60\\t4M\\t*\\t0\\t0\\tACGT\\tIIII\\n'\n"
        )
        fake.chmod(0o755)
        fq = tmp_path / "in_1.fq.gz"
        with gzip.open(fq, "wt") as fh:
            fh.write("@r1\nACGT\n+\nIIII\n")
        cfg = FrameworkConfig(
            genome_dir=os.path.dirname(env["fasta"]),
            genome_fasta_file_name=os.path.basename(env["fasta"]),
            aligner="bwameth",
            bwameth=str(fake),
        )
        outdir = str(tmp_path / "output")
        builder = PipelineBuilder(cfg, env["bam"], outdir=outdir)
        out_bam = str(tmp_path / "aligned.bam")
        builder.run_bwameth(Rule(
            name="align_consensus_unfiltered",
            inputs=[str(fq), str(fq)], outputs=[out_bam], run=None,
        ))
        log = os.path.join(
            outdir, "log", "bwameth_results",
            f"{builder.sample}_consensus_unfiltered.log",
        )
        assert "bwameth-parity-log-line" in open(log).read()
        with BamReader(out_bam) as r:
            assert [rec.qname for rec in r] == ["r1"]
        # final duplex alignment: no log, stderr falls through
        out2 = str(tmp_path / "aligned2.bam")
        builder.run_bwameth(Rule(
            name="align_consensus_unfiltered_duplex",
            inputs=[str(fq), str(fq)], outputs=[out2], run=None,
        ))
        logs = os.listdir(os.path.join(outdir, "log", "bwameth_results"))
        assert logs == [f"{builder.sample}_consensus_unfiltered.log"]

    def test_bwameth_shellout_contract(self, pipeline_env, tmp_path):
        """Fake-binary contract stub (PARITY row 13): run_bwameth must
        invoke `<bwameth> --reference <fasta> -t 8 <fq1> <fq2>` with
        exactly those argv (shell quoting surviving spaces in the fastq
        paths), feed stdout through a real pipe into the SAM->BAM
        writer, and tee stderr to the reference's log path."""
        import json as _json
        import sys as _sys

        from bsseqconsensusreads_tpu.pipeline.stages import PipelineBuilder
        from bsseqconsensusreads_tpu.pipeline.workflow import Rule

        env = pipeline_env
        argv_out = tmp_path / "argv.json"
        fake = tmp_path / "fake_bwameth.py"
        fake.write_text(
            "import json, os, stat, sys\n"
            "json.dump({'argv': sys.argv[1:],\n"
            "           'stdout_is_pipe': stat.S_ISFIFO("
            "os.fstat(1).st_mode)},\n"
            f"          open({str(argv_out)!r}, 'w'))\n"
            "sys.stderr.write('contract-stderr-line\\n')\n"
            "sys.stdout.write('@HD\\tVN:1.6\\tSO:unsorted\\n')\n"
            "sys.stdout.write('@SQ\\tSN:chr1\\tLN:1000\\n')\n"
            "sys.stdout.write("
            "'r1\\t0\\tchr1\\t1\\t60\\t4M\\t*\\t0\\t0\\tACGT\\tIIII\\n')\n"
            "sys.stdout.write("
            "'r2\\t16\\tchr1\\t9\\t60\\t4M\\t*\\t0\\t0\\tTTTT\\tIIII\\n')\n"
        )
        # fastq paths with a space: the argv must arrive as single
        # arguments (stages.run_bwameth shell-quotes them)
        fqdir = tmp_path / "fq dir"
        fqdir.mkdir()
        fq1, fq2 = str(fqdir / "in_1.fq.gz"), str(fqdir / "in_2.fq.gz")
        for fq in (fq1, fq2):
            with gzip.open(fq, "wt") as fh:
                fh.write("@r1\nACGT\n+\nIIII\n")
        cfg = FrameworkConfig(
            genome_dir=os.path.dirname(env["fasta"]),
            genome_fasta_file_name=os.path.basename(env["fasta"]),
            aligner="bwameth",
            bwameth=f"{_sys.executable} {fake}",
        )
        outdir = str(tmp_path / "output")
        builder = PipelineBuilder(cfg, env["bam"], outdir=outdir)
        out_bam = str(tmp_path / "aligned.bam")
        builder.run_bwameth(Rule(
            name="align_consensus_unfiltered",
            inputs=[fq1, fq2], outputs=[out_bam], run=None,
        ))
        seen = _json.load(open(argv_out))
        assert seen["argv"] == [
            "--reference", env["fasta"], "-t", "8", fq1, fq2,
        ]
        assert seen["stdout_is_pipe"] is True
        # pipe wiring: both SAM records came through into the BAM
        with BamReader(out_bam) as r:
            recs = list(r)
        assert [(x.qname, x.flag, x.pos) for x in recs] == [
            ("r1", 0, 0), ("r2", 16, 8),
        ]
        # stderr teed to the reference's log path, exactly once
        log = os.path.join(
            outdir, "log", "bwameth_results",
            f"{builder.sample}_consensus_unfiltered.log",
        )
        assert open(log).read() == "contract-stderr-line\n"


class TestStreaming:
    def _tagged(self, qname, mi, pos):
        r = rec(qname, 99, pos=pos)
        r.set_tag("MI", mi, "Z")
        return r

    def test_adjacent_grouping(self):
        from bsseqconsensusreads_tpu.pipeline.calling import stream_mi_groups

        recs = [self._tagged("a", "1", 0), self._tagged("b", "1", 5),
                self._tagged("c", "2", 10)]
        got = list(stream_mi_groups(recs, grouping="adjacent"))
        assert [(mi, len(g)) for mi, g in got] == [("1", 2), ("2", 1)]

    def test_coordinate_grouping_flushes_and_counts_refragmented(self):
        from bsseqconsensusreads_tpu.pipeline.calling import stream_mi_groups

        stats = StageStats()
        recs = [
            self._tagged("a", "1", 100),
            self._tagged("b", "2", 150),
            self._tagged("c", "2", 200),
            # far downstream: families 1 and 2 must flush before this
            self._tagged("d", "3", 50_000),
            # family 1 reappears after flush -> refragmented
            self._tagged("e", "1", 50_100),
        ]
        got = list(stream_mi_groups(recs, grouping="coordinate", stats=stats))
        mis = [mi for mi, _ in got]
        assert mis == ["1", "2", "3", "1"]
        assert stats.refragmented_families == 1
        assert stats.records_in == 5

    def test_coordinate_streaming_matches_gather_end_to_end(self, pipeline_env):
        env = pipeline_env
        from bsseqconsensusreads_tpu.models.params import ConsensusParams

        with BamReader(env["bam"]) as r:
            recs = list(r)
        a = sorted(
            (x.qname, x.flag, x.seq)
            for x in call_molecular(recs, grouping="gather")
        )
        b = sorted(
            (x.qname, x.flag, x.seq)
            for x in call_molecular(recs, grouping="coordinate")
        )
        assert a == b

    def test_pallas_vote_kernel_matches_xla_end_to_end(self, pipeline_env):
        env = pipeline_env
        with BamReader(env["bam"]) as r:
            recs = list(r)
        a = sorted(
            (x.qname, x.flag, x.seq, x.qual)
            for x in call_molecular(recs, vote_kernel="xla")
        )
        b = sorted(
            (x.qname, x.flag, x.seq, x.qual)
            for x in call_molecular(recs, vote_kernel="pallas")
        )
        # Same records, same spans. Bases may legitimately diverge on
        # exact-likelihood-tie columns (equal posterior; see
        # ops/pallas_vote.py docstring), so bound the divergence instead of
        # asserting bitwise sequence equality — tie-exact comparison lives in
        # tests/test_pallas.py.
        assert [(x[0], x[1], len(x[2])) for x in a] == [
            (x[0], x[1], len(x[2])) for x in b
        ]
        ndiff = sum(
            1
            for x, y in zip(a, b)
            for cx, cy in zip(x[2], y[2])
            if cx != cy
        )
        total = sum(len(x[2]) for x in a)
        assert ndiff <= 0.02 * total, f"{ndiff}/{total} bases differ"


class TestCheckpointedPipeline:
    def test_checkpointed_run_matches_plain_run(self, pipeline_env):
        from bsseqconsensusreads_tpu.config import FrameworkConfig
        from bsseqconsensusreads_tpu.pipeline.stages import run_pipeline

        env = pipeline_env
        base = dict(
            genome_dir=os.path.dirname(env["fasta"]),
            genome_fasta_file_name=os.path.basename(env["fasta"]),
            aligner="self",
            batch_families=4,
            grouping="gather",
        )
        outs = {}
        for tag, every in (("plain", 0), ("ckpt", 2)):
            outdir = str(env["tmp"] / f"out_{tag}")
            cfg = FrameworkConfig(**base, checkpoint_every=every)
            target, _, stats = run_pipeline(cfg, env["bam"], outdir=outdir)
            with BamReader(target) as r:
                outs[tag] = [(x.qname, x.flag, x.pos, x.seq, x.qual) for x in r]
            assert stats["molecular"].batches > 1
            # no scratch left behind
            leftovers = [
                p for p in os.listdir(outdir)
                if ".part" in p or ".ckpt" in p
            ]
            assert leftovers == []
        assert outs["ckpt"] == outs["plain"]


class TestMinReadsFilters:
    def test_duplex_min_reads_filters_families(self, pipeline_env):
        env = pipeline_env
        from bsseqconsensusreads_tpu.io.fasta import FastaFile
        from bsseqconsensusreads_tpu.models.params import ConsensusParams

        cfg = FrameworkConfig(
            genome_dir=os.path.dirname(env["fasta"]),
            genome_fasta_file_name=os.path.basename(env["fasta"]),
            aligner="self",
        )
        outdir = str(env["tmp"] / "output_minreads")
        run_pipeline(cfg, env["bam"], outdir=outdir)
        sample = sample_name(env["bam"])
        aligned = os.path.join(
            outdir, f"{sample}_consensus_unfiltered_aunamerged_aligned.bam"
        )
        fa = FastaFile(env["fasta"])
        with BamReader(aligned) as r:
            names = [n for n, _ in r.header.references]
            recs = list(r)
        # every group has 4 consensus reads; min_reads=5 must drop them all
        stats = StageStats()
        out = list(
            call_duplex(
                recs, fa.fetch, names,
                params=ConsensusParams(min_reads=5), stats=stats,
            )
        )
        assert out == []
        assert stats.skipped_families == stats.families


class TestCli:
    def test_cli_duplex_stage(self, pipeline_env, capsys):
        env = pipeline_env
        from bsseqconsensusreads_tpu.cli import main

        # build the aligned molecular consensus first via the self pipeline
        cfg = FrameworkConfig(
            genome_dir=os.path.dirname(env["fasta"]),
            genome_fasta_file_name=os.path.basename(env["fasta"]),
            aligner="self",
        )
        outdir = str(env["tmp"] / "output5")
        run_pipeline(cfg, env["bam"], outdir=outdir)
        sample = sample_name(env["bam"])
        aligned = os.path.join(
            outdir, f"{sample}_consensus_unfiltered_aunamerged_aligned.bam"
        )
        out = str(env["tmp"] / "cli_duplex.bam")
        rc = main(
            [
                "duplex",
                "-i", aligned,
                "-o", out,
                "--reference", env["fasta"],
                "--mode", "self",
            ]
        )
        assert rc == 0
        with BamReader(out) as r:
            assert len(list(r)) == 24
        err = capsys.readouterr().err
        stats = json.loads(err.strip().splitlines()[-1])
        assert stats["families"] == 12


class TestFgbioTagSurfaceAndPG:
    def test_duplex_tags_and_pg_header(self, pipeline_env):
        env = pipeline_env
        cfg = FrameworkConfig(
            genome_dir=os.path.dirname(env["fasta"]),
            genome_fasta_file_name=os.path.basename(env["fasta"]),
            aligner="self",
        )
        outdir = str(env["tmp"] / "output_tags")
        target, _, _ = run_pipeline(cfg, env["bam"], outdir=outdir)
        with BamReader(target) as r:
            header, duplex = r.header, list(r)
        # @PG provenance chain: molecular stage then duplex stage
        pg = [ln for ln in header.text.splitlines() if ln.startswith("@PG")]
        assert len(pg) == 2
        assert all("PN:bsseqconsensusreads_tpu" in ln for ln in pg)
        assert "PP:" in pg[1] and "PP:" not in pg[0]
        assert "VN:" in pg[0]
        # full fgbio duplex per-strand tag surface — RAW read units
        # (threaded from the molecular cd/ce tags, r4): with 2-4 raw reads
        # per strand every strand depth is >= 2 somewhere
        saw_deep = False
        for d in duplex:
            for tag in ("cD", "cM", "cE", "cd", "ce",
                        "aD", "bD", "aM", "bM", "ad", "bd"):
                assert d.has_tag(tag), tag
            kind, ad = d.get_tag("ad")
            assert kind == "S" and len(ad) == len(d.seq)
            kind, bd = d.get_tag("bd")
            assert kind == "S" and len(bd) == len(d.seq)
            assert d.get_tag("aD") == max(ad) and d.get_tag("bD") == max(bd)
            assert d.get_tag("aM") == min(ad) and d.get_tag("bM") == min(bd)
            assert d.get_tag("aM") >= 1 and d.get_tag("bM") >= 1
            _, cd = d.get_tag("cd")
            assert list(cd) == [a + b for a, b in zip(ad, bd)]
            saw_deep = saw_deep or max(ad) >= 2
        assert saw_deep  # raw units, not strand presence

    def test_pg_chain_unique_ids(self):
        from bsseqconsensusreads_tpu.io.bam import BamHeader

        h = BamHeader("@HD\tVN:1.6\n", [("c", 10)])
        h1 = h.with_pg("toolx", "1.0", "step one")
        h2 = h1.with_pg("toolx", "1.0", "step two")
        pg = [ln for ln in h2.text.splitlines() if ln.startswith("@PG")]
        assert len(pg) == 2
        assert "ID:toolx\t" in pg[0] + "\t"
        assert "ID:toolx.1" in pg[1]
        assert "PP:toolx" in pg[1]


class TestSamToFastqPairing:
    def test_orphans_never_desync_pairs(self, tmp_path):
        """An orphan record must not shift R1/R2 positional pairing
        (bwameth pairs FASTQ entries by line offset, main.snake.py:93)."""
        import gzip as _gzip

        from bsseqconsensusreads_tpu.io.fastq import sam_to_fastq

        def pair(name, n1=True, n2=True):
            out = []
            if n1:
                out.append(rec(name, 0x1 | 0x40, seq="ACGT"))
            if n2:
                out.append(rec(name, 0x1 | 0x80, seq="TTTT"))
            return out

        records = pair("a") + pair("orphan", n2=False) + pair("b") + pair("c")
        fq1, fq2 = str(tmp_path / "r1.fq.gz"), str(tmp_path / "r2.fq.gz")
        n1, n2 = sam_to_fastq(iter(records), fq1, fq2)
        assert (n1, n2) == (3, 3)
        names1 = [l.split("/")[0][1:] for l in _gzip.open(fq1, "rt")
                  if l.startswith("@")]
        names2 = [l.split("/")[0][1:] for l in _gzip.open(fq2, "rt")
                  if l.startswith("@")]
        assert names1 == names2 == ["a", "b", "c"]

    def test_nonadjacent_mates_still_pair(self, tmp_path):
        import gzip as _gzip

        from bsseqconsensusreads_tpu.io.fastq import sam_to_fastq

        records = [
            rec("x", 0x1 | 0x40, seq="AAAA"),
            rec("y", 0x1 | 0x40, seq="CCCC"),
            rec("y", 0x1 | 0x80, seq="GGGG"),
            rec("x", 0x1 | 0x80, seq="TTTT"),
        ]
        fq1, fq2 = str(tmp_path / "r1.fq.gz"), str(tmp_path / "r2.fq.gz")
        n1, n2 = sam_to_fastq(iter(records), fq1, fq2)
        assert (n1, n2) == (2, 2)
        names1 = [l.split("/")[0][1:] for l in _gzip.open(fq1, "rt")
                  if l.startswith("@")]
        names2 = [l.split("/")[0][1:] for l in _gzip.open(fq2, "rt")
                  if l.startswith("@")]
        assert names1 == names2 == ["y", "x"]


class TestReferenceConfigInterop:
    """FrameworkConfig.from_yaml must accept the reference's ACTUAL
    config.yaml (VERDICT round-1 weak item 8): its tool-path keys
    (fgbio/java/picard_path/...) are ignored gracefully, its shared keys
    (genome_dir, genome_fasta_file_name, tmp, bwameth, samtools) bind."""

    REF_CONFIG = "/root/reference/config.yaml"

    @pytest.mark.skipif(
        not os.path.exists(REF_CONFIG), reason="reference not mounted"
    )
    def test_reference_config_loads(self):
        cfg = FrameworkConfig.from_yaml(self.REF_CONFIG)
        assert cfg.genome_dir == "/path/to/genome_dir"
        assert cfg.genome_fasta_file_name == "genome.fa"
        assert cfg.genome_fasta == "/path/to/genome_dir/genome.fa"
        assert cfg.tmp == "/path/to/tmp"
        assert cfg.bwameth == "/path/to/bwameth.py"
        assert cfg.samtools == "/path/to/samtools"
        # unknown JVM-era keys are dropped, never attributes
        for k in ("fgbio", "java", "python3", "picard_path", "tools_dir"):
            assert not hasattr(cfg, k)
        # framework defaults survive alongside reference keys
        assert cfg.backend == "tpu" and cfg.aligner == "self"

    @pytest.mark.skipif(
        not os.path.exists(REF_CONFIG), reason="reference not mounted"
    )
    def test_reference_config_with_overrides(self):
        cfg = FrameworkConfig.from_yaml(
            self.REF_CONFIG, aligner="bwameth", batch_families=64
        )
        assert cfg.aligner == "bwameth" and cfg.batch_families == 64
        assert cfg.bwameth == "/path/to/bwameth.py"


class TestPipelinedYields:
    """The depth-1 dispatch/retire pipeline (calling._pipelined) must emit
    exactly one result per event, in event order — checkpoint resume's
    skip_batches counting depends on it."""

    def test_order_and_count(self):
        from bsseqconsensusreads_tpu.pipeline.calling import _pipelined

        log = []

        def deferred(tag):
            def retire():
                log.append(f"retire:{tag}")
                return [tag]
            return "deferred", retire

        events = [
            deferred("a"),
            ("now", ["b"]),
            ("now", ["c"]),
            deferred("d"),
            deferred("e"),
        ]
        out = list(_pipelined(iter(events)))
        assert out == [["a"], ["b"], ["c"], ["d"], ["e"]]
        # a's retire is deferred until event b arrives; e's runs at drain
        assert log == ["retire:a", "retire:d", "retire:e"]

    def test_empty_and_single(self):
        from bsseqconsensusreads_tpu.pipeline.calling import _pipelined

        assert list(_pipelined(iter([]))) == []
        assert list(_pipelined(iter([("deferred", lambda: [1])]))) == [[1]]


class TestBackendSelection:
    """A broken TPU plugin whose init hangs must never be touched when the
    operator pinned the host backend (BSSEQ_TPU_BACKEND env or config
    backend: cpu) — the site plugin hook bypasses the JAX_PLATFORMS env
    var in both directions, so pinning must ride the jax config before
    any backend init."""

    def test_backend_env_pins_jax_config(self):
        import subprocess
        import sys

        code = (
            "import bsseqconsensusreads_tpu, jax; "
            "print(jax.config.jax_platforms)"
        )
        # drop JAX_PLATFORMS so a shell-level 'cpu' can't make this pass
        # vacuously — the assertion must observe the package hook's pin
        env = {
            k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"
        }
        env["BSSEQ_TPU_BACKEND"] = "cpu"
        r = subprocess.run(
            [sys.executable, "-c", code],
            env=env,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert r.returncode == 0, r.stderr[-500:]
        assert r.stdout.strip().splitlines()[-1] == "cpu"

    def test_unknown_backend_raises(self, tmp_path):
        from bsseqconsensusreads_tpu.pipeline.stages import _apply_backend

        with pytest.raises(WorkflowError, match="backend"):
            _apply_backend("cuda")

    def test_cpu_backend_accepted(self):
        from bsseqconsensusreads_tpu.pipeline.stages import _apply_backend

        _apply_backend("cpu")  # conftest already pinned cpu: no-op, no raise
        _apply_backend("tpu")  # leaves selection untouched


class TestAutoUmiGrouping:
    """The pipeline's GroupReadsByUmi-equivalent pre-stage (config
    group_umis='auto'): a raw aligned BAM with RX but no MI — one step
    EARLIER than the reference's input contract (README.md:7,51-55) —
    runs end to end without fgbio."""

    @pytest.fixture(scope="class")
    def raw_env(self, tmp_path_factory):
        from tests.test_group_umi import make_raw_duplex_records

        tmp = tmp_path_factory.mktemp("rawpipe")
        rng = np.random.default_rng(41)
        name, genome = random_genome(rng, 6000)
        fasta = str(tmp / "genome.fa")
        write_fasta(fasta, name, genome)
        header, records, truth = make_raw_duplex_records(
            rng, name, genome, n_families=6, reads_per_strand=(3, 4)
        )
        bam = str(tmp / "input" / "raw_sample.bam")
        os.makedirs(os.path.dirname(bam), exist_ok=True)
        with BamWriter(bam, header) as w:
            w.write_all(records)
        return {"tmp": tmp, "fasta": fasta, "bam": bam, "truth": truth}

    def test_auto_grouping_full_self_run(self, raw_env):
        env = raw_env
        cfg = FrameworkConfig(
            genome_dir=os.path.dirname(env["fasta"]),
            genome_fasta_file_name=os.path.basename(env["fasta"]),
            aligner="self",
        )
        outdir = str(env["tmp"] / "output")
        target, results, stats = run_pipeline(cfg, env["bam"], outdir=outdir)
        assert [r.name for r in results if r.ran] == [
            "group_reads_by_umi",
            "call_consensus_molecular_tpu",
            "call_duplex_tpu",
        ]
        assert "group" in stats
        n_families = len({f for f, _ in env["truth"].values()})
        assert stats["group"].molecules == n_families
        with BamReader(target) as r:
            duplex = list(r)
        assert len(duplex) == 2 * n_families  # R1+R2 per molecule
        assert all(d.has_tag("MI") and d.has_tag("cD") for d in duplex)
        # rerun: grouped checkpoint honored, nothing re-runs
        _, results2, _ = run_pipeline(cfg, env["bam"], outdir=outdir)
        assert all(not r.ran for r in results2)

    def test_never_grouping_fails_on_raw_input(self, raw_env):
        env = raw_env
        cfg = FrameworkConfig(
            genome_dir=os.path.dirname(env["fasta"]),
            genome_fasta_file_name=os.path.basename(env["fasta"]),
            aligner="self",
            group_umis="never",
        )
        with pytest.raises(Exception, match="MI"):
            run_pipeline(
                cfg, env["bam"], outdir=str(env["tmp"] / "output_never")
            )

    def test_grouped_input_skips_pre_stage(self, pipeline_env):
        from bsseqconsensusreads_tpu.pipeline.stages import PipelineBuilder

        cfg = FrameworkConfig(aligner="self")
        builder = PipelineBuilder(cfg, pipeline_env["bam"], outdir="unused")
        assert not builder._needs_grouping()

    def test_auto_probe_tolerates_umiless_lead_record(self, raw_env, tmp_path):
        """One UMI-less leading record must not flip the 'auto' decision
        for the whole file."""
        from bsseqconsensusreads_tpu.pipeline.stages import PipelineBuilder

        with BamReader(raw_env["bam"]) as r:
            header, records = r.header, list(r)
        lead = records[0].copy()
        lead.qname = "umiless"
        del lead.tags["RX"]
        bam = str(tmp_path / "lead.bam")
        with BamWriter(bam, header) as w:
            w.write_all([lead] + records)
        builder = PipelineBuilder(FrameworkConfig(aligner="self"), bam)
        assert builder._needs_grouping()


class TestWorkflowFilterStage:
    """config `filter:` revives the reference's dead filtered-variant rule
    (main.snake.py:70-80): the workflow inserts a producer for
    `…_unalignedConsensus_molecular_filtered.bam` ahead of SamToFastq."""

    def test_filter_stage_runs_and_feeds_fastq(self, pipeline_env, tmp_path):
        env = pipeline_env
        cfg = FrameworkConfig(
            aligner="none",
            filter={"min_reads": [1], "max_read_error_rate": 1.0,
                    "max_base_error_rate": 1.0, "min_base_quality": 0,
                    "max_no_call_fraction": 1.0},
        )
        outdir = str(tmp_path / "out_filtered")
        target, results, stats = run_pipeline(cfg, env["bam"], outdir=outdir)
        assert [r.name for r in results if r.ran] == [
            "call_consensus_reads_molecular",
            "filter_consensus_molecular",
            "consensus_to_fq_unfiltered",
        ]
        filtered = os.path.join(
            outdir, sample_name(env["bam"]) + "_unalignedConsensus_molecular_filtered.bam"
        )
        assert os.path.exists(filtered)
        assert stats["filter"].kept_records == stats["filter"].records_in > 0
        assert os.path.exists(target)  # fastq 1

    def test_strict_filter_drops_all(self, pipeline_env, tmp_path):
        env = pipeline_env
        cfg = FrameworkConfig(aligner="none", filter={"min_reads": [50]})
        outdir = str(tmp_path / "out_strict")
        _, _, stats = run_pipeline(cfg, env["bam"], outdir=outdir)
        assert stats["filter"].kept_records == 0
        assert stats["filter"].dropped_depth == stats["filter"].templates > 0

    def test_self_mode_filters_duplex_output(self, pipeline_env, tmp_path):
        """Under aligner 'self' the filter runs on the final duplex BAM
        via name-sort -> filter -> coordinate-sort; duplex depth tags
        count strand presence, so [2,1,1] = both strands present."""
        env = pipeline_env
        base_cfg = dict(
            genome_dir=os.path.dirname(env["fasta"]),
            genome_fasta_file_name=os.path.basename(env["fasta"]),
            aligner="self",
        )
        permissive = FrameworkConfig(
            **base_cfg,
            filter={"min_reads": [2, 1, 1], "max_read_error_rate": 1.0,
                    "max_base_error_rate": 1.0, "min_base_quality": 0,
                    "max_no_call_fraction": 1.0},
        )
        outdir = str(tmp_path / "out_selffilter")
        target, results, stats = run_pipeline(permissive, env["bam"], outdir=outdir)
        assert target.endswith("_consensus_duplex_filtered.bam")
        assert [r.name for r in results if r.ran][-1] == "filter_consensus_duplex"
        with BamReader(target) as r:
            kept = list(r)
        # simulator emits both strands for every family: everything survives,
        # and the output is coordinate-sorted
        unfiltered = os.path.join(
            outdir, sample_name(env["bam"]) + "_consensus_duplex_unfiltered.bam"
        )
        with BamReader(unfiltered) as r:
            assert len(kept) == sum(1 for _ in r) > 0
        assert [coordinate_key(r) for r in kept] == sorted(
            coordinate_key(r) for r in kept
        )
        strict = FrameworkConfig(**base_cfg, filter={"min_reads": [50]})
        _, _, stats = run_pipeline(
            strict, env["bam"], outdir=str(tmp_path / "out_selfstrict")
        )
        assert stats["filter"].kept_records == 0

    def test_filter_config_from_yaml(self, tmp_path):
        cfg_path = tmp_path / "c.yaml"
        cfg_path.write_text(
            "aligner: none\nfilter:\n  min_reads: [3, 1, 1]\n"
            "  max_no_call_fraction: 0.5\n"
        )
        cfg = FrameworkConfig.from_yaml(str(cfg_path))
        assert cfg.filter == {"min_reads": [3, 1, 1], "max_no_call_fraction": 0.5}

    def test_bad_filter_config_fails_at_build_time(self, pipeline_env, tmp_path):
        from bsseqconsensusreads_tpu.pipeline.stages import PipelineBuilder

        for bad in ({"min_reads": [1, 3]}, {"min_read": [3]}):
            cfg = FrameworkConfig(aligner="none", filter=bad)
            builder = PipelineBuilder(cfg, pipeline_env["bam"], outdir="x")
            with pytest.raises(WorkflowError, match="invalid `filter:`"):
                builder.build()

    def test_scalar_min_reads_accepted(self, pipeline_env, tmp_path):
        cfg = FrameworkConfig(
            aligner="none",
            filter={"min_reads": 1, "max_read_error_rate": 1.0,
                    "max_base_error_rate": 1.0, "min_base_quality": 0,
                    "max_no_call_fraction": 1.0},
        )
        outdir = str(tmp_path / "out_scalar")
        _, _, stats = run_pipeline(cfg, pipeline_env["bam"], outdir=outdir)
        assert stats["filter"].kept_records > 0

    def test_filter_with_passthrough_rejected(self, pipeline_env):
        from bsseqconsensusreads_tpu.pipeline.stages import PipelineBuilder

        cfg = FrameworkConfig(
            aligner="self", filter={"min_reads": [1]}, duplex_passthrough=True
        )
        builder = PipelineBuilder(cfg, pipeline_env["bam"], outdir="x")
        with pytest.raises(WorkflowError, match="passthrough"):
            builder.build()

    def test_final_headers_declare_coordinate_order(self, pipeline_env, tmp_path):
        env = pipeline_env
        cfg = FrameworkConfig(
            genome_dir=os.path.dirname(env["fasta"]),
            genome_fasta_file_name=os.path.basename(env["fasta"]),
            aligner="self",
            filter={"min_reads": [1], "max_read_error_rate": 1.0,
                    "max_base_error_rate": 1.0, "min_base_quality": 0,
                    "max_no_call_fraction": 1.0},
        )
        outdir = str(tmp_path / "out_hd")
        target, _, _ = run_pipeline(cfg, env["bam"], outdir=outdir)
        for path in (
            target,
            os.path.join(outdir, sample_name(env["bam"]) + "_consensus_duplex_unfiltered.bam"),
        ):
            with BamReader(path) as r:
                assert "SO:coordinate" in r.header.text, path
