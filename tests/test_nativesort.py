"""Native raw-blob external sort vs the Python engine (ISSUE 6).

The native sort (wirepack_sort_raw_records + bamio_merge_runs, behind
pipeline.extsort.resolve_sort_engine) is a pure speed substitution for
the blob-generator + heapq path — any divergence is silent output
corruption. These tests pin byte-identity of the SORTED OUTPUT across
engines: unit-level over adversarial record sets (multi-run merges,
ties, unmapped records, a forced multi-pass merge), stage-level through
the real pipeline across both consensus stages x both alignment modes x
all input policies, under the extsort_spill failpoint (retried run
rewrite), and with an fd + jax.live_arrays census on abandon.
"""

from __future__ import annotations

import hashlib
import os
import random
import struct

import numpy as np
import pytest

from bsseqconsensusreads_tpu.io import native, wirepack
from bsseqconsensusreads_tpu.io.bam import (
    BamHeader,
    BamReader,
    BamRecord,
    BamWriter,
    CMATCH,
    RawRecords,
    encode_record,
)
from bsseqconsensusreads_tpu.pipeline import extsort

pytestmark = pytest.mark.skipif(
    not (wirepack.available() and native.available()),
    reason=f"native libs: {wirepack.load_error()} / {native.load_error()}",
)

HEADER = BamHeader("@HD\tVN:1.6\n", [("chr1", 1 << 20), ("chr2", 1 << 20)])


def _random_blobs(n: int, seed: int, qname_pool: int = 40) -> list[bytes]:
    """Encoded records with heavy key ties (shared qnames/positions),
    unmapped records, and varied lengths — the sort comparator's edge
    surface."""
    rng = random.Random(seed)
    out = []
    for i in range(n):
        ln = rng.choice((8, 12, 20))
        r = BamRecord(
            qname=f"q{rng.randrange(qname_pool)}" + "x" * rng.randrange(3),
            flag=rng.choice((99, 147, 83, 163, 0, 4)),
            ref_id=rng.choice((-1, 0, 0, 1)),
            pos=rng.choice((-1, rng.randrange(64), rng.randrange(4096))),
            mapq=60,
            cigar=[(CMATCH, ln)],
            seq="ACGT" * (ln // 4),
            qual=bytes([rng.randrange(2, 40)] * ln),
        )
        r.set_tag("MI", str(i), "Z")
        out.append(encode_record(r))
    return out


def _sorted_bytes(items, engine: str, buffer_records: int,
                  tmp_path, tag: str) -> bytes:
    path = str(tmp_path / f"{tag}_{engine}.bam")
    with BamWriter(path, HEADER) as w:
        extsort.external_sort_raw_to_writer(
            iter(items), w, HEADER, workdir=str(tmp_path),
            buffer_records=buffer_records, engine=engine,
        )
    with open(path, "rb") as fh:
        return fh.read()


class TestEngineIdentityUnit:
    @pytest.mark.parametrize("buffer_records", [10_000, 700, 97])
    def test_blob_stream_identity(self, tmp_path, buffer_records):
        """No-spill, few-run, and many-run shapes all byte-identical."""
        blobs = _random_blobs(3000, seed=buffer_records)
        a = _sorted_bytes(blobs, "python", buffer_records, tmp_path, "u")
        b = _sorted_bytes(blobs, "native", buffer_records, tmp_path, "u")
        assert a == b and len(a) > 1000

    def test_rawrecords_blocks_split_across_runs(self, tmp_path):
        """RawRecords blocks append whole, so native run boundaries can
        differ from the python engine's record-exact splits — the merged
        output must be identical anyway (contiguous-chunk stability)."""
        blobs = _random_blobs(2400, seed=7)
        items = []
        i = 0
        rng = random.Random(1)
        while i < len(blobs):
            k = rng.randrange(1, 9)
            items.append(RawRecords(b"".join(blobs[i : i + k]),
                                    len(blobs[i : i + k])))
            i += k
        a = _sorted_bytes(items, "python", 150, tmp_path, "rr")
        b = _sorted_bytes(items, "native", 150, tmp_path, "rr")
        assert a == b

    def test_multi_pass_merge_identity(self, tmp_path):
        """> MERGE_FANIN runs forces the pre-merge pass on both engines."""
        old = extsort.MERGE_FANIN
        extsort.MERGE_FANIN = 4
        try:
            blobs = _random_blobs(1200, seed=3)
            a = _sorted_bytes(blobs, "python", 60, tmp_path, "mp")
            b = _sorted_bytes(blobs, "native", 60, tmp_path, "mp")
            assert a == b
        finally:
            extsort.MERGE_FANIN = old

    def test_bamrecord_items_accepted(self, tmp_path):
        recs = [
            BamRecord(qname=f"r{i % 5}", flag=99, ref_id=0, pos=100 - i,
                      mapq=60, cigar=[(CMATCH, 4)], seq="ACGT",
                      qual=bytes([30] * 4))
            for i in range(50)
        ]
        a = _sorted_bytes(recs, "python", 10, tmp_path, "br")
        b = _sorted_bytes(recs, "native", 10, tmp_path, "br")
        assert a == b

    def test_resolve_engine_contract(self, monkeypatch):
        assert extsort.resolve_sort_engine("auto") == "native"
        assert extsort.resolve_sort_engine("python") == "python"
        assert extsort.resolve_sort_engine("native") == "native"
        with pytest.raises(ValueError, match="unknown sort engine"):
            extsort.resolve_sort_engine("frobnicate")
        monkeypatch.setenv("BSSEQ_TPU_SORT_ENGINE", "python")
        assert extsort.resolve_sort_engine("native") == "python"

    def test_sub_phase_attribution_lands(self, tmp_path):
        from bsseqconsensusreads_tpu.utils import observe

        metrics = observe.Metrics()
        blobs = _random_blobs(1500, seed=11)
        path = str(tmp_path / "attr.bam")
        with BamWriter(path, HEADER) as w:
            extsort.external_sort_raw_to_writer(
                iter(blobs), w, HEADER, workdir=str(tmp_path),
                buffer_records=300, metrics=metrics, engine="native",
            )
        secs = metrics.seconds
        assert "sort_write.order" in secs and "sort_write.merge" in secs
        assert "sort_write.merge_bgzf" in secs
        # dotted sub-phases must not inflate the phase summary's host sum
        summary = metrics.phase_summary(1.0)
        host_named = (
            secs.get("sort_write", 0.0) + secs.get("spill_write", 0.0)
        )
        # phase_summary rounds to 3 decimals; the check is that dotted
        # names add ~nothing, not float exactness
        assert summary["host_s"] == pytest.approx(host_named, abs=2e-3)


class TestSpillFaultThroughNativeSort:
    def test_spill_io_error_retried_byte_identical(self, tmp_path):
        """The extsort_spill failpoint fires inside the native engine's
        retried write unit: one injected IO error, one retry, identical
        bytes to the fault-free run."""
        from bsseqconsensusreads_tpu.faults import failpoints
        from bsseqconsensusreads_tpu.utils import observe

        blobs = _random_blobs(900, seed=21)
        clean = _sorted_bytes(blobs, "native", 120, tmp_path, "clean")
        metrics = observe.Metrics()
        failpoints.arm("extsort_spill=io_error:times=1")
        try:
            path = str(tmp_path / "faulted.bam")
            with BamWriter(path, HEADER) as w:
                extsort.external_sort_raw_to_writer(
                    iter(blobs), w, HEADER, workdir=str(tmp_path),
                    buffer_records=120, metrics=metrics, engine="native",
                )
            with open(path, "rb") as fh:
                faulted = fh.read()
        finally:
            failpoints.disarm()
        assert faulted == clean
        assert metrics.counters.get("batches_retried", 0) == 1

    def test_merge_failpoint_fires_on_native_path(self, tmp_path):
        from bsseqconsensusreads_tpu.faults import failpoints

        blobs = _random_blobs(400, seed=22)
        failpoints.arm("extsort_merge=raise:RuntimeError:times=1")
        try:
            with pytest.raises(RuntimeError):
                _sorted_bytes(blobs, "native", 100, tmp_path, "mf")
        finally:
            failpoints.disarm()


class TestAbandonLeakCensus:
    def _fd_count(self) -> int:
        return len(os.listdir("/proc/self/fd"))

    def test_producer_raise_releases_fds_and_tmpdir(self, tmp_path):
        """A producer exception mid-stream must leave no spill tempdir,
        no open run descriptors, and no extra live jax arrays."""
        import gc

        import jax

        blobs = _random_blobs(600, seed=31)

        def items():
            for i, b in enumerate(blobs):
                if i == 450:  # after several spills
                    raise RuntimeError("producer died")
                yield b

        gc.collect()
        fd0 = self._fd_count()
        live0 = len(jax.live_arrays())
        before = set(os.listdir(tmp_path))
        with pytest.raises(RuntimeError, match="producer died"):
            path = str(tmp_path / "abandon.bam")
            with BamWriter(path, HEADER) as w:
                extsort.external_sort_raw_to_writer(
                    iter(items()), w, HEADER, workdir=str(tmp_path),
                    buffer_records=100, engine="native",
                )
        gc.collect()
        leftover = {
            d for d in set(os.listdir(tmp_path)) - before
            if d.startswith("bsseq_extsort_")
        }
        assert leftover == set()
        assert self._fd_count() <= fd0 + 1  # the (closed) output file
        assert len(jax.live_arrays()) <= live0


def _pipeline_digest(tmp_path, tag: str, sort_engine: str, policy: str,
                     records, name: str, genome: str) -> str:
    from bsseqconsensusreads_tpu.config import FrameworkConfig
    from bsseqconsensusreads_tpu.pipeline.stages import run_pipeline
    from bsseqconsensusreads_tpu.utils.testing import write_fasta

    wd = tmp_path / f"{tag}_{sort_engine}_{policy}"
    wd.mkdir()
    fa = str(wd / "g.fa")
    write_fasta(fa, name, genome)
    header = BamHeader(
        "@HD\tVN:1.6\tSO:coordinate\n", [(name, len(genome))]
    )
    inbam = str(wd / "in.bam")
    with BamWriter(inbam, header) as w:
        for r in records:
            w.write(r)
    env_before = os.environ.get("BSSEQ_TPU_INPUT_POLICY")
    os.environ["BSSEQ_TPU_INPUT_POLICY"] = policy
    try:
        cfg = FrameworkConfig(
            genome_dir=str(wd), genome_fasta_file_name="g.fa",
            tmp=str(wd), aligner="self", grouping="coordinate",
            batch_families=7, sort_buffer_records=40,
            sort_engine=sort_engine,
        )
        target, _, _ = run_pipeline(cfg, inbam, outdir=str(wd / "out"))
    finally:
        if env_before is None:
            os.environ.pop("BSSEQ_TPU_INPUT_POLICY", None)
        else:
            os.environ["BSSEQ_TPU_INPUT_POLICY"] = env_before
    with open(target, "rb") as fh:
        return hashlib.sha256(fh.read()).hexdigest()


class TestPipelineIdentityAcrossPolicies:
    """Both stages (molecular + duplex, via the self-aligned pipeline
    whose outputs both ride the raw coordinate sort) x all input
    policies x both engines: one digest per policy, identical across
    engines."""

    @pytest.mark.parametrize("policy", ["strict", "quarantine", "lenient"])
    def test_both_engines_identical(self, tmp_path, policy):
        from bsseqconsensusreads_tpu.utils.testing import (
            make_grouped_bam_records,
            random_genome,
        )

        rng = np.random.default_rng(61)
        name, genome = random_genome(rng, 6000)
        _, records = make_grouped_bam_records(rng, name, genome,
                                              n_families=12)
        digests = {
            eng: _pipeline_digest(
                tmp_path, "pol", eng, policy, records, name, genome
            )
            for eng in ("python", "native")
        }
        assert digests["python"] == digests["native"]


class TestUnalignedModeIdentity:
    """mode='unaligned' emits order-preserving batches (no sort), but the
    stage x engine matrix must still be byte-stable: the emit engines'
    records ride write_batch_stream untouched."""

    def test_molecular_unaligned_both_emit_engines(self, tmp_path):
        from bsseqconsensusreads_tpu.pipeline.calling import (
            StageStats,
            call_molecular_batches,
        )
        from bsseqconsensusreads_tpu.pipeline.extsort import (
            write_batch_stream,
        )
        from bsseqconsensusreads_tpu.utils.testing import (
            make_grouped_bam_records,
            random_genome,
        )

        rng = np.random.default_rng(71)
        name, genome = random_genome(rng, 5000)
        header, records = make_grouped_bam_records(rng, name, genome,
                                                   n_families=8)
        outs = {}
        for emit in ("python", "native"):
            path = str(tmp_path / f"un_{emit}.bam")
            batches = call_molecular_batches(
                iter(records), mode="unaligned", grouping="adjacent",
                batch_families=3, stats=StageStats(), emit=emit,
            )
            write_batch_stream(batches, path, header, "unaligned")
            with open(path, "rb") as fh:
                outs[emit] = fh.read()
        assert outs["python"] == outs["native"] and len(outs["python"]) > 200
