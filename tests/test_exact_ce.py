"""Exact raw-unit duplex error accounting (round-5: PARITY rows 6/12 closure).

Covers the full chain: molecular cB histogram tag invariants -> duplex
exact ce via the conversion-mapped histogram -> ac/bc strand-call tags ->
FilterConsensusReads --require-single-strand-agreement. The load-bearing
case is a strand whose dissenting raw read voted a THIRD base (neither
the strand call nor the duplex call): the r4 approximation (ce = cd -
ce_strand on disagreement) undercounts it; the exact path must not.
"""

from __future__ import annotations

import numpy as np
import pytest

from bsseqconsensusreads_tpu.io.bam import (
    BamHeader,
    BamRecord,
    BamWriter,
    CMATCH,
    write_items,
)
from bsseqconsensusreads_tpu.models.params import ConsensusParams
from bsseqconsensusreads_tpu.pipeline.calling import (
    StageStats,
    call_duplex_batches,
    call_molecular_batches,
)
from bsseqconsensusreads_tpu.pipeline.filter import (
    FilterParams,
    filter_consensus,
)
from bsseqconsensusreads_tpu.utils.testing import (
    make_grouped_bam_records,
    random_genome,
)


def _run_molecular(records, tag):
    out = []
    for batch in call_molecular_batches(
        iter(list(records)), params=ConsensusParams(min_reads=1),
        mode="self", batch_families=6, grouping="coordinate",
        stats=StageStats(), mesh=None,
    ):
        out.extend(batch)
    return out


class TestMolecularBaseCounts:
    @pytest.fixture(scope="class")
    def consensus(self):
        rng = np.random.default_rng(41)
        name, genome = random_genome(rng, 9000)
        _header, records = make_grouped_bam_records(
            rng, name, genome, n_families=14, reads_per_strand=(1, 4),
            error_rate=0.05,
        )
        return _run_molecular(records, "mol")

    def test_cb_is_sparse_dissent_histogram(self, consensus):
        """cB stores the DISSENT histogram: the call plane is zeroed
        (derivable as cd - ce) so the tag deflates to ~nothing; the
        remaining planes sum to ce at called columns, and masked (N)
        columns keep the full histogram (sum == cd)."""
        assert consensus, "no consensus records emitted"
        for rec in consensus:
            _s, cd = rec.get_tag("cd")
            _s, ce = rec.get_tag("ce")
            _s, cb = rec.get_tag("cB")
            cd = np.asarray(cd, np.int64)
            ce = np.asarray(ce, np.int64)
            cb = np.asarray(cb, np.int64).reshape(4, len(cd))
            for i, ch in enumerate(rec.seq):
                if ch == "N":
                    assert cb[:, i].sum() == cd[i], (rec.qname, i)
                    continue
                x = "ACGT".index(ch)
                assert cb[x, i] == 0, (rec.qname, i)
                assert cb[:, i].sum() == ce[i], (rec.qname, i)


def _duplex_family(tmp_path, with_cb=True, third_base=True):
    """One hand-built duplex group: strand A (3 raw reads: 2xG + 1
    dissenter) vs strand B (2 raw reads, both T, higher qual) over an
    all-A reference window (conversion = identity there). The duplex
    merge calls T; strand A's dissenter voted C (third base) when
    third_base, else T. cB tags follow the sparse dissent-histogram
    format (call plane zero — sparsify_base_counts)."""
    L = 20
    pos = 50
    k = 9  # assert column
    genome = "A" * 400
    header = BamHeader("@HD\tVN:1.6\tSO:coordinate\n", [("chrT", 400)])
    a_seq = "G" * L
    b_seq = "T" * L
    recs = []
    for flag, mi, seq, qual, cd, ce, cb in (
        (99, "7/A", a_seq, 30, 3, 1, {"A": 0, "C": 1, "G": 0, "T": 0}),
        (163, "7/B", b_seq, 35, 2, 0, {"A": 0, "C": 0, "G": 0, "T": 0}),
        (83, "7/B", b_seq, 35, 2, 0, {"A": 0, "C": 0, "G": 0, "T": 0}),
        (147, "7/A", a_seq, 30, 3, 1, {"A": 0, "C": 1, "G": 0, "T": 0}),
    ):
        if third_base and cb["C"]:
            pass  # dissenter already votes C
        elif cb["C"]:
            cb = {"A": 0, "C": 0, "G": 0, "T": 1}
        rec = BamRecord(
            qname=f"m{flag}", flag=flag, ref_id=0, pos=pos, mapq=60,
            cigar=[(CMATCH, L)], next_ref_id=0, next_pos=pos, tlen=L,
            seq=seq, qual=bytes([qual] * L),
        )
        rec.set_tag("MI", mi, "Z")
        rec.set_tag("RX", "AAAA-TTTT", "Z")
        rec.tags["cd"] = ("B", ("S", [cd] * L))
        rec.tags["ce"] = ("B", ("S", [ce] * L))
        if with_cb:
            flat = []
            for base in "ACGT":
                flat += [cb[base]] * L
            rec.tags["cB"] = ("B", ("S", flat))
        recs.append(rec)
    recs.sort(key=lambda r: (r.ref_id, r.pos))
    return genome, header, recs, k


def _run_duplex(genome, records, strand_tags=True, emit="python"):
    out = []
    for batch in call_duplex_batches(
        iter(list(records)), lambda n, s, e: genome[s:e], ["chrT"],
        mode="self", batch_families=4, grouping="coordinate",
        stats=StageStats(), mesh=None, strand_tags=strand_tags, emit=emit,
    ):
        out.extend(batch)
    return out


class TestDeepFamilySubtype:
    def test_cb_u16_subtype_past_255(self):
        """A family deep enough that dissent counts exceed 255 must emit
        cB with the u16 ('S') subtype — and shallow families use 'C'."""
        rng = np.random.default_rng(77)
        name, genome = random_genome(rng, 6000)
        _header, records = make_grouped_bam_records(
            rng, name, genome, n_families=1, reads_per_strand=(1200, 1200),
            read_len=30, error_rate=0.9,
        )
        out = _run_molecular(records, "deep")
        assert out
        subs = {rec.get_tag("cB")[0] for rec in out}
        assert "S" in subs
        for rec in out:
            sub, cb = rec.get_tag("cB")
            _s, cd = rec.get_tag("cd")
            _s, ce = rec.get_tag("ce")
            cb = np.asarray(cb, np.int64).reshape(4, len(cd))
            called = np.asarray([ch != "N" for ch in rec.seq])
            np.testing.assert_array_equal(
                cb.sum(axis=0)[called], np.asarray(ce)[called]
            )

    def test_cb_u8_subtype_shallow(self):
        rng = np.random.default_rng(78)
        name, genome = random_genome(rng, 6000)
        _header, records = make_grouped_bam_records(
            rng, name, genome, n_families=2, reads_per_strand=(2, 3),
        )
        out = _run_molecular(records, "shallow")
        assert out and all(rec.get_tag("cB")[0] == "C" for rec in out)


class TestExactDuplexCe:
    def test_third_base_dissenter_counted_exactly(self, tmp_path):
        genome, _header, recs, k = _duplex_family(tmp_path, third_base=True)
        out = _run_duplex(genome, recs)
        r1 = [r for r in out if r.flag & 0x40]  # duplex R1 (merged 99+163)
        assert len(r1) == 1
        rec = r1[0]
        assert rec.seq[k] == "T"  # duplex call = strand B base
        _s, ce = rec.get_tag("ce")
        _s, cd = rec.get_tag("cd")
        # strand A: all 3 raw reads (2xG + 1xC) disagree with T -> 3;
        # strand B: both T reads agree -> 0. The r4 approximation said 2.
        assert int(cd[k]) == 5
        assert int(ce[k]) == 3

    def test_without_third_base_matches_r4_rule(self, tmp_path):
        genome, _header, recs, k = _duplex_family(tmp_path, third_base=False)
        out = _run_duplex(genome, recs)
        rec = [r for r in out if r.flag & 0x40][0]
        # dissenter voted T == duplex call: 2 errors either way
        _s, ce = rec.get_tag("ce")
        assert int(ce[k]) == 2

    def test_without_cb_keeps_r4_approximation(self, tmp_path):
        genome, _header, recs, k = _duplex_family(tmp_path, with_cb=False)
        out = _run_duplex(genome, recs)
        rec = [r for r in out if r.flag & 0x40][0]
        _s, ce = rec.get_tag("ce")
        assert int(ce[k]) == 2  # cd_A - ce_A = 3 - 1 (documented fallback)

    def test_strand_error_tags(self, tmp_path):
        """fgbio's ae/be per-base arrays carry STRAND-vs-own-call units
        (the placed molecular ce), distinct from the duplex-level ce:
        strand A's dissenter is 1 error vs the A call everywhere, strand
        B none; aE/bE are the corresponding read-level rates."""
        genome, _header, recs, k = _duplex_family(tmp_path)
        out = _run_duplex(genome, recs)
        rec = [r for r in out if r.flag & 0x40][0]
        _s, ae = rec.get_tag("ae")
        _s, be = rec.get_tag("be")
        _s, ad = rec.get_tag("ad")
        assert int(ae[k]) == 1 and int(be[k]) == 0
        a_rate = float(rec.get_tag("aE"))
        assert abs(a_rate - sum(ae) / sum(ad)) < 1e-6
        assert float(rec.get_tag("bE")) == 0.0

    def test_strand_call_tags(self, tmp_path):
        genome, _header, recs, k = _duplex_family(tmp_path)
        out = _run_duplex(genome, recs)
        rec = [r for r in out if r.flag & 0x40][0]
        ac = str(rec.get_tag("ac"))
        bc = str(rec.get_tag("bc"))
        assert len(ac) == len(rec.seq) == len(bc)
        assert ac[k] == "G" and bc[k] == "T"

    def test_strand_tags_off(self, tmp_path):
        genome, _header, recs, _k = _duplex_family(tmp_path)
        out = _run_duplex(genome, recs, strand_tags=False)
        rec = [r for r in out if r.flag & 0x40][0]
        assert not rec.has_tag("ac") and not rec.has_tag("bc")

    def test_native_emit_matches_python(self, tmp_path):
        from bsseqconsensusreads_tpu.io import wirepack

        if not wirepack.available():
            pytest.skip(f"native wirepack: {wirepack.load_error()}")
        genome, header, recs, _k = _duplex_family(tmp_path)
        blobs = {}
        for emit in ("python", "native"):
            out = str(tmp_path / f"d_{emit}.bam")
            with BamWriter(out, header, engine="python") as w:
                write_items(w, _run_duplex(genome, recs, emit=emit))
            blobs[emit] = open(out, "rb").read()
        assert blobs["python"] == blobs["native"]

    def test_native_ingest_carries_cb(self, tmp_path):
        """The C columnar parser must deliver cB to the sidecar: duplex
        output over GroupedColumnarStream == over Python records,
        including the exact-ce column the histogram changes."""
        from bsseqconsensusreads_tpu.pipeline import ingest

        if not ingest.available():
            pytest.skip("native ingest unavailable")
        genome, header, recs, k = _duplex_family(tmp_path)
        src = str(tmp_path / "mol_in.bam")
        with BamWriter(src, header, engine="python") as w:
            w.write_all(recs)
        stream = ingest.GroupedColumnarStream(
            src, strip_suffix=True, scan_policy="duplex",
            grouping="coordinate",
        )
        out_native = []
        from bsseqconsensusreads_tpu.pipeline.calling import StageStats

        for batch in call_duplex_batches(
            stream, lambda n, s, e: genome[s:e], ["chrT"],
            mode="self", batch_families=4, grouping="coordinate",
            stats=StageStats(), mesh=None,
        ):
            out_native.extend(batch)
        rec = [r for r in out_native if r.flag & 0x40][0]
        _s, ce = rec.get_tag("ce")
        assert int(ce[k]) == 3  # exact value, not the r4 approximation


class TestMixedBatches:
    def test_mixed_cb_batch_no_crash(self, tmp_path):
        """One batch mixing a cB family, a cd-only family, and a family
        with no consensus tags at all must not crash the exact pass
        (review finding: entry-less families' init spans indexed out of
        bounds) and must keep per-family semantics."""
        genome, _header, recs, k = _duplex_family(tmp_path, with_cb=True)
        # family 2: cd/ce but no cB (r4 fallback); family 3: no tags
        g2, _h2, recs2, _k2 = _duplex_family(tmp_path, with_cb=False)
        recs3 = []
        for r in recs2:
            r2 = r.copy()
            r2.tags = dict(r.tags)
            mi = str(r2.get_tag("MI"))
            r2.tags["MI"] = ("Z", "8" + mi[1:])
            r2.pos += 40
            recs3.append(r2)
        recs4 = []
        for r in recs2:
            r4 = r.copy()
            r4.tags = {
                "MI": ("Z", "9" + str(r4.get_tag("MI"))[1:]),
                "RX": r4.tags["RX"],
            }
            r4.pos += 80
            recs4.append(r4)
        allrecs = sorted(
            recs + recs3 + recs4, key=lambda r: (r.ref_id, r.pos)
        )
        out = _run_duplex(genome, allrecs)
        by_mi = {}
        for rec in out:
            if rec.flag & 0x40:
                by_mi[str(rec.get_tag("MI"))] = rec
        assert set(by_mi) == {"7", "8", "9"}
        _s, ce7 = by_mi["7"].get_tag("ce")
        _s, ce8 = by_mi["8"].get_tag("ce")
        assert int(ce7[k]) == 3  # exact (cB)
        assert int(ce8[k]) == 2  # r4 rule (no cB)
        _s, cd9 = by_mi["9"].get_tag("cd")
        assert int(cd9[k]) == 2  # presence units (no tags at all)
        # strand-error quartet: present on raw-unit families, OMITTED on
        # the presence-unit family (no raw info — claiming aE=0 would
        # pass fgbio error filters it never measured against)
        assert by_mi["7"].has_tag("ae") and by_mi["7"].has_tag("aE")
        assert not by_mi["9"].has_tag("ae")
        assert not by_mi["9"].has_tag("aE")


class TestUnalignedOrientation:
    """Per-base tags follow the emitted SEQ orientation (review finding:
    reverse-role unaligned records stored window-order arrays against a
    revcomped SEQ)."""

    def test_molecular_unaligned_reverse_tags_flip(self):
        rng = np.random.default_rng(51)
        name, genome = random_genome(rng, 8000)
        _header, records = make_grouped_bam_records(
            rng, name, genome, n_families=4, reads_per_strand=(2, 2),
            error_rate=0.05,
        )
        outs = {}
        for mode in ("self", "unaligned"):
            outs[mode] = {}
            for batch in call_molecular_batches(
                iter(list(records)), params=ConsensusParams(min_reads=1),
                mode=mode, batch_families=4, grouping="coordinate",
                stats=StageStats(), mesh=None,
            ):
                for rec in batch:
                    key = (str(rec.get_tag("MI")), bool(rec.flag & 0x80))
                    outs[mode][key] = rec
        flipped = 0
        for key, srec in outs["self"].items():
            urec = outs["unaligned"][key]
            _s, scd = srec.get_tag("cd")
            _s, ucd = urec.get_tag("cd")
            _s, scb = srec.get_tag("cB")
            _s, ucb = urec.get_tag("cB")
            n = len(scd)
            if urec.seq == srec.seq:  # forward-emitted role
                assert list(ucd) == list(scd)
                assert list(ucb) == list(scb)
                continue
            flipped += 1
            from bsseqconsensusreads_tpu.io.fastq import reverse_complement

            assert urec.seq == reverse_complement(srec.seq)
            assert list(ucd) == list(scd)[::-1]
            s4 = np.asarray(scb).reshape(4, n)
            u4 = np.asarray(ucb).reshape(4, n)
            np.testing.assert_array_equal(u4, s4[::-1, ::-1])
            _s, sce = srec.get_tag("ce")
            _s, uce = urec.get_tag("ce")
            assert list(uce) == list(sce)[::-1]
        assert flipped  # reverse roles existed

    def test_duplex_unaligned_reverse_ac_revcomp(self, tmp_path):
        from bsseqconsensusreads_tpu.io.fastq import reverse_complement

        genome, _header, recs, _k = _duplex_family(tmp_path)
        by = {}
        for mode in ("self", "unaligned"):
            out = []
            for batch in call_duplex_batches(
                iter(list(recs)), lambda n, s, e: genome[s:e], ["chrT"],
                mode=mode, batch_families=4, grouping="coordinate",
                stats=StageStats(), mesh=None,
            ):
                out.extend(batch)
            by[mode] = {r.flag & 0x80: r for r in out}
        s2, u2 = by["self"][0x80], by["unaligned"][0x80]
        assert u2.seq == reverse_complement(s2.seq)
        assert str(u2.get_tag("ac")) == reverse_complement(
            str(s2.get_tag("ac"))
        )
        _s, sad = s2.get_tag("ad")
        _s, uad = u2.get_tag("ad")
        assert list(uad) == list(sad)[::-1]

    def test_unaligned_native_emit_matches_python(self, tmp_path):
        from bsseqconsensusreads_tpu.io import wirepack

        if not wirepack.available():
            pytest.skip(f"native wirepack: {wirepack.load_error()}")
        genome, header, recs, _k = _duplex_family(tmp_path)
        blobs = {}
        for emit in ("python", "native"):
            out = []
            for batch in call_duplex_batches(
                iter(list(recs)), lambda n, s, e: genome[s:e], ["chrT"],
                mode="unaligned", batch_families=4, grouping="coordinate",
                stats=StageStats(), mesh=None, emit=emit,
            ):
                out.extend(batch)
            p = str(tmp_path / f"u_{emit}.bam")
            with BamWriter(p, header, engine="python") as w:
                write_items(w, out)
            blobs[emit] = open(p, "rb").read()
        assert blobs["python"] == blobs["native"]


class TestZipperTagReorientation:
    def test_reverse_strand_graft_flips_arrays(self):
        from bsseqconsensusreads_tpu.io.bam import FREVERSE
        from bsseqconsensusreads_tpu.pipeline.record_ops import zipper_bams

        src = BamRecord(
            qname="t", flag=0x4 | 0x1 | 0x8, ref_id=-1, pos=-1, mapq=0,
            cigar=[], next_ref_id=-1, next_pos=-1, tlen=0,
            seq="ACGT", qual=b"\x1e" * 4,
        )
        src.tags["cd"] = ("B", ("S", [1, 2, 3, 4]))
        src.tags["cB"] = ("B", ("S", list(range(16))))
        src.tags["ac"] = ("Z", "ACGN")
        aligned = BamRecord(
            qname="t", flag=0x1 | FREVERSE, ref_id=0, pos=10, mapq=60,
            cigar=[(CMATCH, 4)], next_ref_id=0, next_pos=10, tlen=4,
            seq="ACGT", qual=b"\x1e" * 4,
        )
        out = zipper_bams([aligned], [src])[0]
        assert list(out.get_tag("cd")[1]) == [4, 3, 2, 1]
        # cB: planes complemented (A<->T, C<->G) + columns reversed
        got = list(out.get_tag("cB")[1])
        want = [
            v
            for p in (3, 2, 1, 0)
            for v in list(range(16))[p * 4 : (p + 1) * 4][::-1]
        ]
        assert got == want
        assert str(out.get_tag("ac")) == "NCGT"

    def test_forward_graft_untouched(self):
        from bsseqconsensusreads_tpu.pipeline.record_ops import zipper_bams

        src = BamRecord(
            qname="t", flag=0x4 | 0x1 | 0x8, ref_id=-1, pos=-1, mapq=0,
            cigar=[], next_ref_id=-1, next_pos=-1, tlen=0,
            seq="ACGT", qual=b"\x1e" * 4,
        )
        src.tags["cd"] = ("B", ("S", [1, 2, 3, 4]))
        aligned = BamRecord(
            qname="t", flag=0x1, ref_id=0, pos=10, mapq=60,
            cigar=[(CMATCH, 4)], next_ref_id=0, next_pos=10, tlen=4,
            seq="ACGT", qual=b"\x1e" * 4,
        )
        out = zipper_bams([aligned], [src])[0]
        assert list(out.get_tag("cd")[1]) == [1, 2, 3, 4]


class TestFilterProbe:
    def test_probe_raises_before_write(self, tmp_path):
        from bsseqconsensusreads_tpu.io.bam import BamHeader as BH
        from bsseqconsensusreads_tpu.pipeline.filter import (
            probe_strand_tag_support,
        )

        genome, header, recs, _k = _duplex_family(tmp_path)
        out = _run_duplex(genome, recs, strand_tags=False)
        p = str(tmp_path / "noac.bam")
        with BamWriter(p, header, engine="python") as w:
            write_items(w, out)
        params = FilterParams(
            min_reads=(1,), require_single_strand_agreement=True
        )
        with pytest.raises(ValueError, match="ac/bc"):
            probe_strand_tag_support(p, params)
        # without -s the probe is a no-op
        probe_strand_tag_support(p, FilterParams(min_reads=(1,)))


class TestSingleStrandAgreementFilter:
    def _duplex_records(self, tmp_path):
        genome, _header, recs, k = _duplex_family(tmp_path)
        return _run_duplex(genome, recs), k

    def test_disagreeing_column_masked(self, tmp_path):
        out, k = self._duplex_records(tmp_path)
        params = FilterParams(
            min_reads=(1,), max_base_error_rate=1.0,
            max_read_error_rate=1.0, max_no_call_fraction=1.0,
            require_single_strand_agreement=True,
        )
        kept = list(filter_consensus(iter(out), params))
        assert kept, "template unexpectedly dropped"
        rec = [r for r in kept if r.flag & 0x40][0]
        assert rec.seq[k] == "N" and rec.qual[k] == 2

    def test_agreement_not_masked_without_flag(self, tmp_path):
        out, k = self._duplex_records(tmp_path)
        params = FilterParams(
            min_reads=(1,), max_base_error_rate=1.0,
            max_read_error_rate=1.0, max_no_call_fraction=1.0,
        )
        kept = list(filter_consensus(iter(out), params))
        rec = [r for r in kept if r.flag & 0x40][0]
        assert rec.seq[k] == "T"

    def test_strand_error_rate_drop(self, tmp_path):
        """fgbio applies --max-read-error-rate to each single-strand
        consensus too: strand A's aE (1 dissenter of 3 raw reads per
        column) trips a threshold the duplex-level cE would pass."""
        genome, _header, recs, _k = _duplex_family(tmp_path)
        out = _run_duplex(genome, recs)
        rec = [r for r in out if r.flag & 0x40][0]
        a_rate = float(rec.get_tag("aE"))
        assert a_rate > 0.25  # 1/3 dissent on every strand-A column
        params = FilterParams(
            min_reads=(1,), max_read_error_rate=0.25,
            max_base_error_rate=1.0, max_no_call_fraction=1.0,
        )
        kept = list(filter_consensus(iter(out), params))
        assert not kept  # strand-level rate drops the template

    def test_strand_base_error_rate_masks(self, tmp_path):
        genome, _header, recs, k = _duplex_family(tmp_path)
        out = _run_duplex(genome, recs)
        params = FilterParams(
            min_reads=(1,), max_read_error_rate=1.0,
            max_base_error_rate=0.3,  # strand A: ae/ad = 1/3 > 0.3
            max_no_call_fraction=1.0,
        )
        kept = list(filter_consensus(iter(out), params))
        rec = [r for r in kept if r.flag & 0x40][0]
        assert rec.seq[k] == "N"  # masked by the strand-A base rate

    def test_missing_tags_raise(self, tmp_path):
        genome, _header, recs, _k = _duplex_family(tmp_path)
        out = _run_duplex(genome, recs, strand_tags=False)
        params = FilterParams(
            min_reads=(1,), require_single_strand_agreement=True,
            max_read_error_rate=1.0, max_no_call_fraction=1.0,
        )
        with pytest.raises(ValueError, match="ac/bc"):
            list(filter_consensus(iter(out), params))
