"""Molecular consensus kernel vs scalar oracle + semantics tests."""

import numpy as np
import pytest

from bsseqconsensusreads_tpu.models.molecular import (
    molecular_consensus,
    overlap_cocall,
)
from bsseqconsensusreads_tpu.models.params import ConsensusParams
from bsseqconsensusreads_tpu.ops.encode import (
    NBASE,
    encode_molecular_families,
    iter_mi_groups,
)
from bsseqconsensusreads_tpu.utils.oracle import oracle_molecular_family
from bsseqconsensusreads_tpu.utils.testing import make_grouped_bam_records, random_genome


def random_family(rng, T, W, n_frac=0.1):
    bases = rng.integers(0, 4, size=(T, 2, W)).astype(np.int8)
    quals = rng.integers(2, 41, size=(T, 2, W)).astype(np.uint8)
    mask = rng.random((T, 2, W)) < n_frac
    bases[mask] = NBASE
    quals[bases == NBASE] = 0
    return bases, quals


PARAM_SETS = [
    ConsensusParams(),
    ConsensusParams(consensus_call_overlapping_bases=False),
    ConsensusParams(error_rate_pre_umi=20.0, error_rate_post_umi=10.0),
    ConsensusParams(min_input_base_quality=15),
    ConsensusParams(min_consensus_base_quality=30),
]


class TestKernelVsOracle:
    @pytest.mark.parametrize("pi", range(len(PARAM_SETS)))
    def test_matches_oracle(self, pi):
        rng = np.random.default_rng(1000 + pi)
        params = PARAM_SETS[pi]
        T, W = 5, 24
        bases, quals = random_family(rng, T, W)
        got = molecular_consensus(bases[None], quals[None], params)
        want = oracle_molecular_family(bases.tolist(), quals.tolist(), params)
        np.testing.assert_array_equal(np.asarray(got["base"][0]), np.array(want["base"]))
        np.testing.assert_array_equal(np.asarray(got["depth"][0]), np.array(want["depth"]))
        np.testing.assert_array_equal(np.asarray(got["errors"][0]), np.array(want["errors"]))
        # quals can differ by 1 at rounding boundaries (float32 vs float64)
        dq = np.abs(
            np.asarray(got["qual"][0], np.int32) - np.array(want["qual"], np.int32)
        )
        assert dq.max() <= 1

    def test_batch_of_families(self):
        rng = np.random.default_rng(2000)
        params = ConsensusParams()
        F, T, W = 6, 4, 16
        all_b, all_q = [], []
        for _ in range(F):
            b, q = random_family(rng, T, W)
            all_b.append(b)
            all_q.append(q)
        bases = np.stack(all_b)
        quals = np.stack(all_q)
        got = molecular_consensus(bases, quals, params)
        for f in range(F):
            want = oracle_molecular_family(bases[f].tolist(), quals[f].tolist(), params)
            np.testing.assert_array_equal(np.asarray(got["base"][f]), np.array(want["base"]))


class TestSemantics:
    def test_unanimous_high_qual(self):
        # 4 agreeing T observations -> consensus T with high quality.
        T, W = 4, 8
        bases = np.full((T, 2, W), 3, dtype=np.int8)
        quals = np.full((T, 2, W), 35, dtype=np.uint8)
        out = molecular_consensus(bases[None], quals[None], ConsensusParams())
        assert (np.asarray(out["base"][0]) == 3).all()
        assert (np.asarray(out["depth"][0]) == 4).all()
        assert (np.asarray(out["errors"][0]) == 0).all()
        # pre-UMI error rate 45 caps the final quality at ~45
        assert np.asarray(out["qual"][0]).max() <= 46

    def test_majority_wins(self):
        bases = np.full((3, 2, 4), 0, dtype=np.int8)
        bases[2] = 2  # one dissenting G vs two As
        quals = np.full((3, 2, 4), 30, dtype=np.uint8)
        out = molecular_consensus(
            bases[None], quals[None], ConsensusParams(consensus_call_overlapping_bases=False)
        )
        assert (np.asarray(out["base"][0]) == 0).all()
        assert (np.asarray(out["errors"][0]) == 1).all()

    def test_no_coverage_is_no_call(self):
        bases = np.full((2, 2, 6), NBASE, dtype=np.int8)
        quals = np.zeros((2, 2, 6), dtype=np.uint8)
        out = molecular_consensus(bases[None], quals[None], ConsensusParams())
        assert (np.asarray(out["base"][0]) == NBASE).all()
        assert (np.asarray(out["qual"][0]) == 2).all()
        assert (np.asarray(out["depth"][0]) == 0).all()

    def test_single_read_passthrough(self):
        # Depth-1 family: consensus equals the read, qual bounded by the read.
        W = 10
        bases = np.full((1, 2, W), NBASE, dtype=np.int8)
        quals = np.zeros((1, 2, W), dtype=np.uint8)
        read = np.array([0, 1, 2, 3, 0, 1, 2, 3, 0, 1], dtype=np.int8)
        bases[0, 0] = read
        quals[0, 0] = 30
        out = molecular_consensus(bases[None], quals[None], ConsensusParams())
        np.testing.assert_array_equal(np.asarray(out["base"][0, 0]), read)
        assert (np.asarray(out["base"][0, 1]) == NBASE).all()
        assert np.asarray(out["qual"][0, 0]).max() <= 31

    def test_overlap_cocall_agreement_boosts(self):
        # R1 and R2 agree on the overlap: co-call doubles the evidence weight.
        bases = np.zeros((1, 2, 4), dtype=np.int8)
        quals = np.full((1, 2, 4), 20, dtype=np.uint8)
        b2, q2 = overlap_cocall(bases.astype(np.int8), quals.astype(np.float32))
        assert (np.asarray(q2) == 40.0).all()
        assert (np.asarray(b2) == 0).all()

    def test_overlap_cocall_disagreement(self):
        bases = np.zeros((1, 2, 1), dtype=np.int8)
        bases[0, 1, 0] = 2
        quals = np.zeros((1, 2, 1), dtype=np.float32)
        quals[0, 0, 0] = 30.0
        quals[0, 1, 0] = 20.0
        b2, q2 = overlap_cocall(bases, quals)
        assert np.asarray(b2[0, 0, 0]) == 0 and np.asarray(b2[0, 1, 0]) == 0
        assert np.asarray(q2[0, 0, 0]) == 10.0
        # exact tie -> masked
        quals[0, 1, 0] = 30.0
        b3, _ = overlap_cocall(bases, quals)
        assert (np.asarray(b3)[0, :, 0] == NBASE).all()


class TestEncoder:
    def test_encode_synthetic_families(self, rng):
        name, genome = random_genome(rng, 2000)
        _, records = make_grouped_bam_records(rng, name, genome, n_families=5)
        groups = iter_mi_groups(records)
        batch, skipped = encode_molecular_families(groups)
        assert not skipped
        assert len(batch.meta) == 10  # 5 families x 2 strands
        f, t, w = batch.shape
        assert w % 128 == 0
        # every family window must contain at least one observation
        assert ((batch.bases != NBASE).any(axis=(1, 2, 3))).all()
        # encoded bases at covered positions are 0..3
        covered = batch.bases != NBASE
        assert batch.bases[covered].min() >= 0 and batch.bases[covered].max() <= 3

    def test_missing_mi_raises(self, rng):
        from bsseqconsensusreads_tpu.io.bam import BamRecord

        rec = BamRecord(qname="q", flag=99, seq="ACGT", qual=bytes([30] * 4))
        with pytest.raises(ValueError, match="MI tag"):
            iter_mi_groups([rec])

    def test_encoder_consensus_end_to_end(self, rng):
        # Error-free family: consensus must reproduce the bisulfite-converted
        # genome windows exactly.
        name, genome = random_genome(rng, 1000)
        _, records = make_grouped_bam_records(
            rng, name, genome, n_families=3, error_rate=0.0
        )
        groups = iter_mi_groups(records)
        batch, _ = encode_molecular_families(groups)
        out = molecular_consensus(batch.bases, batch.quals, ConsensusParams())
        base = np.asarray(out["base"])
        depth = np.asarray(out["depth"])
        for fi, meta in enumerate(batch.meta):
            for role in range(2):
                cov = depth[fi, role] > 0
                assert cov.any()
                # reconstruct expected from any input read of that role
                fam_bases = batch.bases[fi, :, role, :]
                for t in range(fam_bases.shape[0]):
                    read_cov = fam_bases[t] != NBASE
                    if read_cov.any():
                        np.testing.assert_array_equal(
                            base[fi, role][read_cov], fam_bases[t][read_cov]
                        )


def test_bucketed_batching_cuts_pad_waste_same_output():
    """Depth-homogeneous chunking (_group_batches_bucketed) must reduce
    template-padding waste on a cfDNA-like depth mixture while emitting
    exactly the same consensus records (order may differ across chunks).

    The pad-waste claim is about the PADDED [F,T,2,W] envelope, so both
    modes pin layout="padded" explicitly: under the segment-packed
    default (PR 9) pad_waste's denominator is packed rows actually
    issued, where depth bucketing has nothing left to cut (bucketing
    under packed exists to bound compile shapes, not FLOPs — ROADMAP
    "Packed everywhere"). A packed-layout leg still pins the identity
    half: bucketed == sequential bytes on the default layout too."""
    import numpy as np

    from bsseqconsensusreads_tpu.pipeline.calling import (
        StageStats,
        call_molecular_batches,
    )
    from bsseqconsensusreads_tpu.utils.testing import stream_duplex_families

    codes = np.random.default_rng(3).integers(0, 4, size=50_000).astype(np.int8)
    recs = list(
        stream_duplex_families(
            codes, 600, read_len=60,
            templates_for=lambda fam: 1 if fam % 10 < 7 else 3,
        )
    )
    results = {}
    for mode in ("sequential", "bucketed"):
        for layout in ("padded", "packed"):
            stats = StageStats()
            out = [
                r
                for b in call_molecular_batches(
                    iter(recs), grouping="adjacent", stats=stats,
                    mesh=None, batching=mode, layout=layout,
                )
                for r in b
            ]
            results[(mode, layout)] = (
                stats.pad_waste,
                sorted(
                    (r.qname, r.flag, r.seq, bytes(r.qual)) for r in out
                ),
            )
    assert (
        results[("bucketed", "padded")][0]
        < results[("sequential", "padded")][0] - 0.05
    )
    # identity holds per layout AND across layouts
    expected = results[("sequential", "padded")][1]
    for key, (_, recs_out) in results.items():
        assert recs_out == expected, key


def test_interior_nocall_emits_contiguous_N_not_compacted():
    """A depth-0 column INSIDE a consensus read's span (tie-masked overlap
    co-call at depth 1) must emit as N/qual-2 with the span contiguous —
    compacting it would shift every downstream base against the M-run
    CIGAR (round-3 accuracy-eval finding; fgbio emits no-call N bases)."""
    from bsseqconsensusreads_tpu.io.bam import BamRecord, CMATCH
    from bsseqconsensusreads_tpu.pipeline.calling import call_molecular

    L = 30
    genome = ("ACGT" * 10)[:L]
    # one template whose R1/R2 fully overlap; disagree at column 7 with
    # EQUAL quals -> overlap co-call masks both observations there
    seq1 = list(genome)
    seq2 = list(genome)
    seq1[7] = "A" if genome[7] != "A" else "C"
    recs = []
    for role, flag, seq in ((0, 99, seq1), (1, 147, seq2)):
        r = BamRecord(
            qname="t0", flag=flag, ref_id=0, pos=0, mapq=60,
            cigar=[(CMATCH, L)], next_ref_id=0, next_pos=0,
            seq="".join(seq), qual=bytes([30] * L),
        )
        r.set_tag("MI", "5/A", "Z")
        recs.append(r)
    out = list(call_molecular(iter(recs), mode="self", grouping="adjacent"))
    assert len(out) == 2
    for rec in out:
        assert len(rec.seq) == L  # contiguous: the hole is not compacted
        assert rec.seq[7] == "N"
        assert rec.qual[7] == 2
        assert rec.seq[:7] == genome[:7] and rec.seq[8:] == genome[8:]
        tags = dict(rec.tags)
        assert tags["cd"][1][1][7] == 0  # per-base depth records the hole
        assert tags["cM"][1] == 0


def test_singleton_host_path_matches_device(monkeypatch):
    """T==1 batches take the host cocall+LUT fast path
    (models.molecular.singleton_consensus_host); its records must be
    identical to the device kernel's, tag for tag."""
    from bsseqconsensusreads_tpu.pipeline.calling import call_molecular
    from bsseqconsensusreads_tpu.utils.testing import (
        make_grouped_bam_records,
        random_genome,
    )

    local = np.random.default_rng(424242)
    name, genome = random_genome(local, 3000)
    _, records = make_grouped_bam_records(
        local, name, genome, n_families=6, reads_per_strand=(1, 1),
        error_rate=0.02,
    )

    def surface(recs):
        return [
            (
                r.qname, r.flag, r.pos, r.seq, r.qual,
                tuple(r.get_tag("cd")[1]), tuple(r.get_tag("ce")[1]),
                int(r.get_tag("cD")), float(r.get_tag("cE")),
            )
            for r in recs
        ]

    fast = surface(call_molecular([r.copy() for r in records], mode="self"))
    monkeypatch.setenv("BSSEQ_TPU_SINGLETON", "0")
    slow = surface(call_molecular([r.copy() for r in records], mode="self"))
    assert fast == slow and fast


def test_singleton_host_path_exhaustive_base_qual():
    """Every (base, qual 0-255) single observation: the host fast path must
    reproduce the device kernel's base/qual/depth/errors exactly — incl.
    the low-qual argmax flip (error prob > 0.75 makes every OTHER base
    likelier) and mask behavior the r4 review caught."""
    from bsseqconsensusreads_tpu.models.molecular import (
        molecular_consensus,
        singleton_consensus_host,
    )

    params = ConsensusParams(min_reads=1)
    w = 256
    bases = np.full((4, 1, 2, w), NBASE, np.int8)
    quals = np.zeros((4, 1, 2, w), np.float32)
    for fb in range(4):  # family index = observed base
        bases[fb, 0, 0, :] = fb  # lone R1 observation per column
        quals[fb, 0, 0, :] = np.arange(w, dtype=np.float32)
    dev = {k: np.asarray(v) for k, v in molecular_consensus(
        bases, quals, params).items()}
    host = singleton_consensus_host(bases, quals, params)
    for key in ("base", "qual", "depth", "errors"):
        np.testing.assert_array_equal(host[key], dev[key], err_msg=key)
