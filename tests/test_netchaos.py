"""graftnet tests: wire faults, epoch fencing, shared-nothing shipping.

* grammar — the net actions (delay/drop/dup/corrupt/half_open/
  partition) parse, are gated to the net_* sites, take the @peer
  predicate, and fold into a WirePlan; mangle() produces a frame the
  guarded decoder must refuse (bad_json);
* refusal matrix — each injected wire fault against a real tcp server:
  partition refuses the connection, drop kills one delivery and the
  retry heals, dup answers from the rid cache with NO second dispatch,
  corrupt (either direction) is refused at decode, half_open is
  bounded by the client's own timeout;
* fencing — EpochBook mint/persist/restart continuity, per-lease epoch
  mint, stale-epoch publish refused (`publish_fenced`) with duplicate
  commits still tolerated, adopt/revoke/check with lease-scoped revoke
  (the stale-renewer race), and the durable-write gate installed into
  pipeline.checkpoint;
* renewal race — the pump self-fences when its local deadline lapses
  behind a partition and on a lease_expired reply, and a pump that
  outlived its lease can never fence the next one;
* ship byte-identity — work_loop in ship mode (inputs fetched, outputs
  pushed as small CRC chunks over real tcp) merges to the
  single-process SHA for 1- and 3-slice runs.

Everything here is in-process (tier-1); the subprocess fleet versions
of these faults live in tools/chaos_drill.py.
"""

import dataclasses
import hashlib
import json
import os
import threading
import time

import numpy as np
import pytest

from bsseqconsensusreads_tpu.config import FrameworkConfig
from bsseqconsensusreads_tpu.elastic import (
    Coordinator,
    SliceLedger,
    config_doc,
    fencing,
    merge as merge_mod,
    slice_name,
    split_input,
    worker as worker_mod,
)
from bsseqconsensusreads_tpu.elastic.coordinator import (
    ENV_CHUNK_B,
    ENV_COORDINATOR_ADDR,
    ENV_WORKER_ID,
    chunk_bytes,
)
from bsseqconsensusreads_tpu.faults import failpoints, integrity, netchaos
from bsseqconsensusreads_tpu.io.bam import BamWriter
from bsseqconsensusreads_tpu.pipeline import checkpoint as ckpt_mod
from bsseqconsensusreads_tpu.serve import transport
from bsseqconsensusreads_tpu.serve.server import ProtocolServer
from bsseqconsensusreads_tpu.utils.testing import (
    make_grouped_bam_records,
    random_genome,
    write_fasta,
)


@pytest.fixture(autouse=True)
def _clean_slate():
    """Failpoints and the adopted fence are process-global: every test
    leaves them as it found them (unarmed, unfenced, gate removed)."""
    yield
    failpoints.disarm()
    fencing.release()
    ckpt_mod.install_write_gate(None)


def _events(path):
    out = []
    if os.path.exists(path):
        with open(path) as fh:
            for line in fh:
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    return out


def _sha(path):
    with open(path, "rb") as fh:
        return hashlib.sha256(fh.read()).hexdigest()


# ---------------------------------------------------------------------------
# fault grammar + WirePlan folding


class TestGrammar:
    def test_net_actions_parse(self):
        pts = failpoints.parse_schedule(
            "net_send=delay;net_recv=drop;net_send=dup;net_recv=corrupt;"
            "net_accept=half_open:0.4s;net_send=partition"
        )
        actions = [p.action for p in pts]
        assert actions == [
            "delay", "drop", "dup", "corrupt", "half_open", "partition"
        ]
        assert pts[0].duration_s == 0.2  # delay default
        assert pts[4].duration_s == 0.4

    def test_delay_takes_duration(self):
        (fp,) = failpoints.parse_schedule("net_send=delay:1.5s")
        assert fp.duration_s == 1.5

    def test_net_actions_gated_to_net_sites(self):
        with pytest.raises(failpoints.FailpointError, match="net_"):
            failpoints.parse_schedule("dispatch_kernel=drop")
        with pytest.raises(failpoints.FailpointError, match="net_"):
            failpoints.parse_schedule("elastic_publish=partition")

    def test_process_actions_stay_legal_at_net_sites(self):
        (fp,) = failpoints.parse_schedule("net_send=stall:0.1s")
        assert fp.action == "stall"

    def test_peer_predicate_is_substring(self):
        (fp,) = failpoints.parse_schedule("net_send=partition@peer=10.0.0.9")
        assert fp.peer == "10.0.0.9"
        assert fp.matches({"peer": "tcp:10.0.0.9:8600"})
        assert not fp.matches({"peer": "tcp:10.0.0.8:8600"})

    def test_unknown_predicate_names_peer(self):
        with pytest.raises(failpoints.FailpointError, match="peer"):
            failpoints.parse_schedule("net_send=drop@host=x")

    def test_plan_folds_fired_points(self):
        failpoints.arm("net_send=delay:0.05s;net_send=dup;net_recv=drop")
        p = netchaos.plan("net_send", peer="tcp:h:1")
        assert p and p.delay_s == 0.05 and p.dup
        assert not p.drop and not p.partition
        r = netchaos.plan("net_recv", peer="tcp:h:1")
        assert r.drop and not r.dup

    def test_plan_quiet_when_unarmed(self):
        failpoints.disarm()
        assert not netchaos.plan("net_send", peer="anything")

    def test_peer_gates_plan(self):
        failpoints.arm("net_send=partition@peer=10.9.9.9")
        assert not netchaos.plan("net_send", peer="tcp:127.0.0.1:1")
        assert netchaos.plan("net_send", peer="tcp:10.9.9.9:1").partition

    def test_mangle_is_refused_by_decoder(self):
        body = json.dumps({"op": "ping"}).encode()
        bad = netchaos.mangle(body)
        assert bad != body and len(bad) == len(body)
        with pytest.raises(transport.TransportError) as ei:
            transport._decode(bad, transport.MAX_FRAME)
        assert ei.value.reason == "bad_json"
        assert netchaos.mangle(b"") == b""

    def test_chunk_bytes_clamped(self, monkeypatch):
        monkeypatch.delenv(ENV_CHUNK_B, raising=False)
        assert chunk_bytes() == 1 << 20
        monkeypatch.setenv(ENV_CHUNK_B, "512")
        assert chunk_bytes() == 512
        monkeypatch.setenv(ENV_CHUNK_B, str(64 << 20))
        assert chunk_bytes() == 4 << 20  # one chunk must fit one frame
        monkeypatch.setenv(ENV_CHUNK_B, "nonsense")
        assert chunk_bytes() == 1 << 20


# ---------------------------------------------------------------------------
# the refusal matrix over real sockets


class _Echo(ProtocolServer):
    """One-op server counting real dispatches — the idempotency meter."""

    def __init__(self, *a, **k):
        super().__init__(*a, **k)
        self.dispatches = 0

    def _dispatch(self, req):
        self.dispatches += 1
        return {"ok": True, "echo": req.get("n")}

    def _on_drain(self):
        pass


class TestWireFaults:
    @pytest.fixture()
    def echo(self):
        srv = _Echo(addresses=["tcp:127.0.0.1:0"])
        # graftlint: owned-thread -- test fixture accept loop, drained
        # in teardown
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        deadline = time.monotonic() + 10.0
        while not srv.bound and time.monotonic() < deadline:
            time.sleep(0.01)
        assert srv.bound
        yield srv, srv.bound[0]
        failpoints.disarm()
        srv.request_drain()
        t.join(timeout=10.0)

    def test_partition_refuses_then_heals(self, echo):
        srv, addr = echo
        failpoints.arm("net_send=partition")
        with pytest.raises(ConnectionError, match="injected partition"):
            transport.request(addr, {"op": "e", "n": 1}, timeout=5.0)
        assert srv.dispatches == 0
        failpoints.disarm()
        assert transport.request(addr, {"op": "e", "n": 2}, timeout=5.0)["ok"]

    def test_drop_kills_one_delivery_retry_heals(self, echo):
        srv, addr = echo
        failpoints.arm("net_send=drop@hit=1")
        with pytest.raises(ConnectionError, match="injected drop"):
            transport.request(addr, {"op": "e", "n": 1}, timeout=5.0)
        resp = transport.request(addr, {"op": "e", "n": 2}, timeout=5.0)
        assert resp["ok"] and resp["echo"] == 2
        assert srv.dispatches == 1

    def test_dup_answered_from_rid_cache(self, echo, monkeypatch, tmp_path):
        srv, addr = echo
        sink = str(tmp_path / "ledger.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        failpoints.arm("net_send=dup@peer=tcp:")
        resp = transport.request(addr, {"op": "e", "n": 7}, timeout=5.0)
        assert resp["ok"] and resp["echo"] == 7
        # the duplicate frame (same _rid) earned NO second dispatch
        assert srv.dispatches == 1
        dups = [e for e in _events(sink) if e.get("event") == "frame_dup_ignored"]
        assert len(dups) == 1 and dups[0]["op"] == "e"

    def test_corrupt_request_refused_as_bad_json(self, echo):
        srv, addr = echo
        # @peer=tcp: matches only the CLIENT edge (the server's peer is
        # the bare accepted address) — the request frame is mangled, the
        # server's framing refuses it without dispatching
        failpoints.arm("net_send=corrupt@peer=tcp:")
        resp = transport.request(addr, {"op": "e", "n": 1}, timeout=5.0)
        assert not resp["ok"] and resp["guard"] == "bad_json"
        assert srv.dispatches == 0

    def test_corrupt_reply_refused_as_bad_json(self, echo):
        srv, addr = echo
        # hits count matching evaluations: 1 = client send, 2 = server
        # answering — the REPLY is mangled, this client must refuse it
        failpoints.arm("net_send=corrupt@hit=2")
        with pytest.raises(transport.TransportError) as ei:
            transport.request(addr, {"op": "e", "n": 1}, timeout=5.0)
        assert ei.value.reason == "bad_json"
        assert srv.dispatches == 1

    def test_half_open_bounded_by_client_timeout(self, echo):
        srv, addr = echo
        failpoints.arm("net_accept=half_open:2s")
        t0 = time.monotonic()
        with pytest.raises(OSError):
            transport.request(addr, {"op": "e", "n": 1}, timeout=0.5)
        assert time.monotonic() - t0 < 1.8  # the client's timeout, not 2s
        assert srv.dispatches == 0

    def test_accept_drop_is_no_response(self, echo):
        srv, addr = echo
        failpoints.arm("net_accept=drop@hit=1")
        with pytest.raises(ConnectionError):
            transport.request(addr, {"op": "e", "n": 1}, timeout=5.0)
        assert srv.dispatches == 0
        assert transport.request(addr, {"op": "e", "n": 2}, timeout=5.0)["ok"]

    def test_delay_slows_but_delivers(self, echo):
        srv, addr = echo
        failpoints.arm("net_send=delay:0.3s@peer=tcp:")
        t0 = time.monotonic()
        resp = transport.request(addr, {"op": "e", "n": 3}, timeout=5.0)
        assert resp["ok"] and time.monotonic() - t0 >= 0.3
        assert srv.dispatches == 1


# ---------------------------------------------------------------------------
# epoch fencing


def _fake_rundir(tmp_path, n=2):
    rundir = str(tmp_path / "run")
    specs = []
    for sid in range(n):
        os.makedirs(os.path.join(rundir, "slices", slice_name(sid)),
                    exist_ok=True)
        specs.append({
            "sid": sid,
            "path": os.path.join("slices", f"{slice_name(sid)}.bam"),
            "records": 5 + sid,
            "families": 2,
            "family_crc": 1000 + sid,
            "input_crc": 0,
        })
    return rundir, specs


def _out(rundir, sid, payload=b"consensus-bytes"):
    path = os.path.join(rundir, "slices", slice_name(sid), "out.bam")
    with open(path, "wb") as fh:
        fh.write(payload)
    return {
        "slice": slice_name(sid),
        "output": "out.bam",
        "crc": integrity.file_crc32(path),
        "family_crc": 1000 + sid,
        "records_out": 2,
    }


class TestFencing:
    def test_epoch_book_mints_and_persists(self, tmp_path):
        book = fencing.EpochBook(str(tmp_path))
        assert book.mint() == 1 and book.mint() == 2
        with open(os.path.join(str(tmp_path), fencing.FENCE_DOC)) as fh:
            assert json.load(fh) == {"epoch": 2}

    def test_epoch_book_restart_continuity(self, tmp_path):
        fencing.EpochBook(str(tmp_path)).mint()
        reborn = fencing.EpochBook(str(tmp_path))
        assert reborn.mint() == 2  # strictly above every granted epoch

    def test_lease_mints_epoch_restart_stays_above(self, tmp_path):
        rundir, specs = _fake_rundir(tmp_path, n=1)
        first = SliceLedger(rundir, specs, lease_s=30.0)
        assert first.lease("wa")["fence_epoch"] == 1
        # coordinator restart: fresh ledger over the same rundir
        second = SliceLedger(rundir, specs, lease_s=30.0)
        assert second.lease("wb")["fence_epoch"] == 2

    def test_stale_epoch_publish_fenced(self, tmp_path, monkeypatch):
        sink = str(tmp_path / "ledger.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        rundir, specs = _fake_rundir(tmp_path, n=1)
        ledger = SliceLedger(rundir, specs, lease_s=0.05)
        zombie = ledger.lease("wz")
        time.sleep(0.1)
        assert ledger.expire_scan() == 1
        retaker = ledger.lease("wr")
        assert retaker["fence_epoch"] > zombie["fence_epoch"]
        manifest = _out(rundir, 0)
        resp = ledger.commit(
            zombie["lease_id"], 0, manifest, worker="wz",
            epoch=zombie["fence_epoch"],
        )
        assert resp == {
            "ok": False, "reason": "fenced",
            "epoch": retaker["fence_epoch"],
        }
        fenced = [e for e in _events(sink) if e.get("event") == "publish_fenced"]
        assert len(fenced) == 1
        assert fenced[0]["worker"] == "wz"
        assert fenced[0]["current"] == retaker["fence_epoch"]
        # the live holder's publish commits
        assert ledger.commit(
            retaker["lease_id"], 0, manifest, worker="wr",
            epoch=retaker["fence_epoch"],
        ) == {"ok": True}

    def test_zombie_fenced_even_with_matching_bytes(self, tmp_path):
        """Fencing outranks the duplicate-commit path: a superseded
        holder gets the typed refusal even when its output is identical
        — an "ok" would invite it to keep writing."""
        rundir, specs = _fake_rundir(tmp_path, n=1)
        ledger = SliceLedger(rundir, specs, lease_s=0.05)
        zombie = ledger.lease("wz")
        time.sleep(0.1)
        ledger.expire_scan()
        retaker = ledger.lease("wr")
        manifest = _out(rundir, 0)
        assert ledger.commit(
            retaker["lease_id"], 0, manifest, worker="wr",
            epoch=retaker["fence_epoch"],
        ) == {"ok": True}
        resp = ledger.commit(
            zombie["lease_id"], 0, manifest, worker="wz",
            epoch=zombie["fence_epoch"],
        )
        assert resp["reason"] == "fenced"

    def test_duplicate_commit_same_epoch_tolerated(self, tmp_path):
        rundir, specs = _fake_rundir(tmp_path, n=1)
        ledger = SliceLedger(rundir, specs, lease_s=30.0)
        grant = ledger.lease("wa")
        manifest = _out(rundir, 0)
        kw = dict(worker="wa", epoch=grant["fence_epoch"])
        assert ledger.commit(grant["lease_id"], 0, manifest, **kw)["ok"]
        dup = ledger.commit(grant["lease_id"], 0, manifest, **kw)
        assert dup == {"ok": True, "duplicate": True}

    def test_adopt_check_revoke_release(self):
        fencing.adopt(3, "lease-a")
        fencing.check("anything")  # live fence: no-op
        fencing.revoke("stale pump", lease_id="lease-b")
        assert not fencing.is_revoked()  # wrong lease: no-op
        fencing.revoke("deadline passed", lease_id="lease-a")
        assert fencing.is_revoked()
        with pytest.raises(fencing.FencedError) as ei:
            fencing.check("shard write")
        assert ei.value.epoch == 3 and "deadline passed" in str(ei.value)
        fencing.release()
        fencing.check("anything")  # released: unfenced again

    def test_revoke_without_adopt_is_noop(self):
        fencing.release()
        fencing.revoke("nothing adopted")
        assert not fencing.is_revoked()

    def test_adopt_installs_checkpoint_write_gate(self):
        fencing.adopt(5, "lease-g")
        fencing.revoke(lease_id="lease-g")
        with pytest.raises(fencing.FencedError):
            ckpt_mod._gate("ckpt shard write")


# ---------------------------------------------------------------------------
# the renewal-race regression


class _BeatStub:
    def beat(self, **kw):
        pass


class _LeaseRefuser(ProtocolServer):
    def _dispatch(self, req):
        return {"ok": False, "reason": "lease_expired"}

    def _on_drain(self):
        pass


class TestRenewalRace:
    def test_deadline_lapse_behind_partition_self_fences(self):
        """No coordinator at all (connection refused every tick): the
        pump must NOT spin forever on 'transient' errors — its local
        deadline lapses and it revokes the fence it was renewing."""
        fencing.adopt(4, "lease-dead")
        stop = threading.Event()
        t0 = time.monotonic()
        worker_mod._renew_lease(
            "tcp:127.0.0.1:9", "wx", "lease-dead", 0.6, stop, _BeatStub()
        )
        assert time.monotonic() - t0 < 10.0
        assert fencing.is_revoked()
        with pytest.raises(fencing.FencedError):
            fencing.check("publish")

    def test_lease_expired_reply_revokes_immediately(self):
        srv = _LeaseRefuser(addresses=["tcp:127.0.0.1:0"])
        # graftlint: owned-thread -- test fixture accept loop, drained
        # below
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        deadline = time.monotonic() + 10.0
        while not srv.bound and time.monotonic() < deadline:
            time.sleep(0.01)
        try:
            fencing.adopt(6, "lease-gone")
            stop = threading.Event()
            worker_mod._renew_lease(
                srv.bound[0], "wx", "lease-gone", 0.9, stop, _BeatStub()
            )
            assert fencing.is_revoked()
        finally:
            srv.request_drain()
            t.join(timeout=10.0)

    def test_stop_wins_without_revoking(self):
        fencing.adopt(7, "lease-live")
        stop = threading.Event()
        stop.set()  # joiner already asked: first wait returns instantly
        worker_mod._renew_lease(
            "tcp:127.0.0.1:9", "wx", "lease-live", 0.3, stop, _BeatStub()
        )
        assert not fencing.is_revoked()

    def test_stale_pump_cannot_fence_next_lease(self):
        """The race the lease-scoped revoke closes: a pump stuck past
        the joiner's patience wakes AFTER the worker adopted its next
        lease — its revoke must be a no-op against the new fence."""
        fencing.adopt(9, "lease-new")
        fencing.revoke("old pump deadline", lease_id="lease-old")
        assert not fencing.is_revoked()
        fencing.check("publish")  # the new lease is untouched


# ---------------------------------------------------------------------------
# ship-mode byte identity (in-process work_loop over real tcp)


N_FAMILIES = 8


@pytest.fixture(scope="module")
def ship_env(tmp_path_factory):
    from bsseqconsensusreads_tpu.pipeline.stages import run_pipeline

    tmp = tmp_path_factory.mktemp("ship")
    rng = np.random.default_rng(1807)
    name, genome = random_genome(rng, 5000)
    fasta = str(tmp / "genome.fa")
    write_fasta(fasta, name, genome)
    header, records = make_grouped_bam_records(
        rng, name, genome, n_families=N_FAMILIES, error_rate=0.01
    )
    bam = str(tmp / "ship.bam")
    with BamWriter(bam, header) as w:
        w.write_all(records)
    cfg = FrameworkConfig(
        genome_dir=os.path.dirname(fasta),
        genome_fasta_file_name=os.path.basename(fasta),
        aligner="self",
    )
    sp_cfg = dataclasses.replace(cfg, tmp=str(tmp / "sp_tmp"))
    target, _results, _stats = run_pipeline(
        sp_cfg, bam, outdir=str(tmp / "single")
    )
    return {"bam": bam, "cfg": cfg, "sp_sha": _sha(target)}


class TestShipByteIdentity:
    @pytest.mark.parametrize("slices", [1, 3])
    def test_ship_work_loop_matches_single_process(
        self, ship_env, tmp_path, monkeypatch, slices
    ):
        """Shared-nothing: the worker fetches every slice input and
        pushes every output over the wire as 512-byte CRC chunks (many
        chunks per slice, so the resumable framing is really exercised)
        — and the merge still equals the single-process SHA."""
        monkeypatch.setenv(ENV_CHUNK_B, "512")
        monkeypatch.setenv(ENV_WORKER_ID, "ws0")
        monkeypatch.setenv(ENV_COORDINATOR_ADDR, "")
        outdir = str(tmp_path / "out")
        rundir = os.path.join(outdir, "elastic")
        os.makedirs(rundir, exist_ok=True)
        cfg = ship_env["cfg"]
        specs = split_input(ship_env["bam"], rundir, slices)
        assert all(
            os.path.getsize(os.path.join(rundir, sl["path"])) > 512
            for sl in specs
        )  # every slice really crosses the wire in multiple chunks
        ledger = SliceLedger(rundir, specs, lease_s=30.0)
        server = Coordinator(
            ledger, config_doc(cfg), addresses=["tcp:127.0.0.1:0"],
            ship=True,
        )
        server.start_monitor()
        # graftlint: owned-thread -- test coordinator accept loop,
        # drained below
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        try:
            deadline = time.monotonic() + 10.0
            while not server.bound and time.monotonic() < deadline:
                time.sleep(0.01)
            processed = worker_mod.work_loop(
                server.bound[0], worker_id="ws0"
            )
        finally:
            server.request_drain()
            thread.join(timeout=10.0)
        assert processed == slices
        target, report = merge_mod.finalize(
            cfg, ship_env["bam"], outdir, specs, ledger.manifests()
        )
        assert report["ok"], report["checks"]
        assert _sha(target) == ship_env["sp_sha"]

    def test_ship_fetch_resends_through_drops(
        self, ship_env, tmp_path, monkeypatch
    ):
        """A dropped chunk request mid-fetch is retried from the same
        offset (`slice_chunk_resent`) and the assembled input passes
        the whole-file CRC — bytes survive a lossy wire."""
        sink = str(tmp_path / "ledger.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        monkeypatch.setenv(ENV_CHUNK_B, "512")
        rundir = str(tmp_path / "run")
        specs = split_input(ship_env["bam"], rundir, 1)
        ledger = SliceLedger(rundir, specs, lease_s=30.0)
        server = Coordinator(
            ledger, config_doc(ship_env["cfg"]),
            addresses=["tcp:127.0.0.1:0"], ship=True,
        )
        # graftlint: owned-thread -- test coordinator accept loop,
        # drained below
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        try:
            deadline = time.monotonic() + 10.0
            while not server.bound and time.monotonic() < deadline:
                time.sleep(0.01)
            # drop the 2nd and 3rd fetch requests on the client edge
            failpoints.arm(
                "net_send=drop@hit=2@peer=tcp:;net_send=drop@hit=3@peer=tcp:"
            )
            dest = str(tmp_path / "fetched.bam")
            worker_mod._fetch_slice(
                server.bound[0], specs[0], dest, worker="wf"
            )
        finally:
            failpoints.disarm()
            server.request_drain()
            thread.join(timeout=10.0)
        src = os.path.join(rundir, specs[0]["path"])
        assert open(dest, "rb").read() == open(src, "rb").read()
        resends = [
            e for e in _events(sink)
            if e.get("event") == "slice_chunk_resent"
        ]
        assert len(resends) >= 2
        assert all(e["attempt"] >= 1 for e in resends)

    def test_push_with_stale_epoch_raises_fenced(
        self, ship_env, tmp_path, monkeypatch
    ):
        """slice_push under a superseded epoch must raise FencedError
        locally — a zombie may not even land BYTES, let alone a
        manifest."""
        monkeypatch.setenv(ENV_CHUNK_B, "512")
        rundir, specs = _fake_rundir(tmp_path, n=1)
        with open(os.path.join(rundir, "slices", "slice0000.bam"), "wb") as fh:
            fh.write(b"x" * 64)
        ledger = SliceLedger(rundir, specs, lease_s=0.05)
        server = Coordinator(
            ledger, {"doc": True}, addresses=["tcp:127.0.0.1:0"], ship=True,
        )
        # graftlint: owned-thread -- test coordinator accept loop,
        # drained below
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        try:
            deadline = time.monotonic() + 10.0
            while not server.bound and time.monotonic() < deadline:
                time.sleep(0.01)
            zombie = ledger.lease("wz")
            time.sleep(0.1)
            ledger.expire_scan()
            ledger.lease("wr")  # supersedes: epoch moves past the zombie
            payload = str(tmp_path / "pushed.bam")
            with open(payload, "wb") as fh:
                fh.write(b"z" * 2048)
            with pytest.raises(fencing.FencedError):
                worker_mod._push_output(
                    server.bound[0], 0, zombie["lease_id"],
                    zombie["fence_epoch"], payload, worker="wz",
                )
        finally:
            server.request_drain()
            thread.join(timeout=10.0)
