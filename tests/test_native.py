"""Native C++ codec vs pure-Python codec: byte-identical behavior."""

import os

import numpy as np
import pytest

from bsseqconsensusreads_tpu.io import native
from bsseqconsensusreads_tpu.io.bam import BamHeader, BamReader, BamRecord, BamWriter, CMATCH
from bsseqconsensusreads_tpu.io.bgzf import BgzfReader, BgzfWriter
from bsseqconsensusreads_tpu.ops.encode import codes_to_seq
from bsseqconsensusreads_tpu.utils.testing import make_grouped_bam_records, random_genome

pytestmark = pytest.mark.skipif(
    not native.available(), reason=f"native codec unavailable: {native.load_error()}"
)


@pytest.fixture(scope="module")
def sample_bam(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("native")
    rng = np.random.default_rng(61)
    name, genome = random_genome(rng, 4000)
    header, records = make_grouped_bam_records(rng, name, genome, n_families=15)
    path = str(tmp / "s.bam")
    with BamWriter(path, header, engine="python") as w:
        w.write_all(records)
    return path, header, records


class TestNativeBgzf:
    def test_read_matches_python(self, sample_bam):
        path, _, _ = sample_bam
        with native.NativeBgzfReader(path) as nr:
            native_bytes = nr.read_all()
        with BgzfReader.open(path) as pr:
            python_bytes = pr.read_all()
        assert native_bytes == python_bytes

    def test_write_readable_by_python(self, tmp_path):
        path = str(tmp_path / "x.bgzf")
        payload = bytes(np.random.default_rng(0).integers(0, 256, 200_000, np.uint8))
        with native.NativeBgzfWriter(path) as w:
            for i in range(0, len(payload), 7919):
                w.write(payload[i : i + 7919])
        with BgzfReader.open(path) as r:
            assert r.read_all() == payload

    def test_truncation_detected(self, sample_bam, tmp_path):
        path, _, _ = sample_bam
        data = open(path, "rb").read()
        bad = str(tmp_path / "trunc.bam")
        open(bad, "wb").write(data[:-28])  # strip EOF marker
        r = native.NativeBgzfReader(bad)
        with pytest.raises(IOError, match="EOF marker"):
            r.read_all()

    def test_not_bgzf(self, tmp_path):
        p = str(tmp_path / "junk")
        open(p, "wb").write(b"\x00" * 64)
        r = native.NativeBgzfReader(p)
        with pytest.raises(IOError, match="not a BGZF"):
            r.read(10)


class TestMtBgzfReader:
    """Parallel-inflate reader (bamio_open_mt): identical byte stream and
    error surface to the single-threaded path — the read-side twin of the
    MT writer."""

    def _multiblock(self, tmp_path, mb: int = 8) -> tuple[str, bytes]:
        payload = bytes(
            np.random.default_rng(5).integers(0, 256, mb << 20, np.uint8)
        )
        path = str(tmp_path / "big.bgzf")
        with native.NativeBgzfWriter(path, threads=3) as w:
            w.write(payload)
        return path, payload

    def test_bytes_identical_to_single_thread(self, tmp_path):
        path, payload = self._multiblock(tmp_path)
        with native.NativeBgzfReader(path, threads=3) as mt:
            mt_bytes = mt.read_all()
        with native.NativeBgzfReader(path, threads=1) as st:
            st_bytes = st.read_all()
        assert mt_bytes == st_bytes == payload

    def test_small_reads_cross_block_boundaries(self, tmp_path):
        path, payload = self._multiblock(tmp_path, mb=1)
        got = []
        with native.NativeBgzfReader(path, threads=3) as r:
            while True:
                b = r.read(7919)
                if not b:
                    break
                got.append(b)
        assert b"".join(got) == payload

    def test_truncation_detected(self, sample_bam, tmp_path):
        path, _, _ = sample_bam
        data = open(path, "rb").read()
        bad = str(tmp_path / "trunc.bam")
        open(bad, "wb").write(data[:-28])  # strip EOF marker
        with native.NativeBgzfReader(bad, threads=3) as r:
            with pytest.raises(IOError, match="EOF marker"):
                r.read_all()

    def test_corrupt_block_detected(self, tmp_path):
        path, _ = self._multiblock(tmp_path, mb=1)
        data = bytearray(open(path, "rb").read())
        data[len(data) // 2] ^= 0xFF  # flip a byte inside some block
        bad = str(tmp_path / "corrupt.bgzf")
        open(bad, "wb").write(bytes(data))
        with native.NativeBgzfReader(bad, threads=3) as r:
            with pytest.raises(IOError, match="inflate|CRC|truncated|BGZF"):
                r.read_all()

    def test_grouped_parse_identical_under_mt(self, sample_bam, monkeypatch):
        """The columnar + grouped parse paths open readers internally; the
        env knob must give them MT inflate with identical output."""
        from bsseqconsensusreads_tpu.pipeline import ingest

        path, _, _ = sample_bam

        def families(policy):
            return [
                (f.mi, [(r.qname, r.flag, r.pos, r.seq, r.qual)
                        for r in f.records])
                if hasattr(f, "records") else
                (f[0], [(r.qname, r.flag, r.pos, r.seq, r.qual)
                        for r in f[1]])
                for f in ingest.GroupedColumnarStream(
                    path, scan_policy=policy
                ).iter_groups()
            ]

        monkeypatch.setenv("BSSEQ_TPU_BGZF_THREADS", "3")
        mt = families("drop")
        monkeypatch.setenv("BSSEQ_TPU_BGZF_THREADS", "1")
        st = families("drop")
        assert mt == st and len(mt) > 0


class TestNativeBamReader:
    def test_records_match(self, sample_bam):
        path, _, records = sample_bam
        with BamReader(path, engine="native") as r:
            got = list(r)
        assert len(got) == len(records)
        for a, b in zip(records, got):
            assert (a.qname, a.flag, a.pos, a.seq, a.qual, a.cigar, a.tags) == (
                b.qname, b.flag, b.pos, b.seq, b.qual, b.cigar, b.tags,
            )


class TestColumnar:
    def test_columnar_matches_records(self, sample_bam):
        path, _, records = sample_bam
        batches = list(native.read_columnar(path, batch_records=64))
        total = sum(b.n for b in batches)
        assert total == len(records)
        i = 0
        for b in batches:
            for j in range(b.n):
                rec = records[i]
                assert int(b.flag[j]) == rec.flag
                assert int(b.pos[j]) == rec.pos
                assert int(b.ref_id[j]) == rec.ref_id
                o, ln = int(b.var_off[j]), int(b.l_seq[j])
                assert codes_to_seq(b.seq[o : o + ln].astype(np.int8)) == rec.seq
                assert bytes(b.qual[o : o + ln]) == rec.qual
                assert b.qname[j].decode() == rec.qname
                assert b.mi[j].decode() == rec.get_tag("MI")
                assert b.rx[j].decode() == rec.get_tag("RX")
                co, nc = int(b.cigar_off[j]), int(b.n_cigar[j])
                cigs = [(int(v) & 0xF, int(v) >> 4) for v in b.cigar[co : co + nc]]
                assert cigs == rec.cigar
                i += 1

    def test_small_var_capacity_still_complete(self, sample_bam):
        # capacity stops must hand the blocking record to the next batch
        path, _, records = sample_bam
        batches = list(native.read_columnar(path, batch_records=1 << 16, var_bytes=4096))
        assert sum(b.n for b in batches) == len(records)
        assert len(batches) > 1


class TestPerf:
    def test_native_decode_faster(self, tmp_path):
        import time

        rng = np.random.default_rng(62)
        name, genome = random_genome(rng, 20000)
        header, records = make_grouped_bam_records(
            rng, name, genome, n_families=300, reads_per_strand=(3, 5)
        )
        path = str(tmp_path / "perf.bam")
        with BamWriter(path, header, engine="python") as w:
            w.write_all(records)
        t0 = time.process_time()
        n_py = sum(1 for _ in BamReader(path, engine="python"))
        t_py = time.process_time() - t0
        t0 = time.process_time()
        n_nat = sum(b.n for b in native.read_columnar(path))
        t_nat = time.process_time() - t0
        assert n_py == n_nat
        # columnar native parse should beat Python records comfortably
        assert t_nat < t_py, f"native {t_nat:.3f}s not faster than python {t_py:.3f}s"


class TestMtBgzfWriter:
    """The threaded BGZF writer must produce byte-identical files to the
    single-threaded writer (independent per-block deflate + in-order
    writes), at every size class including sub-block and multi-block."""

    def test_mt_output_identical_and_valid(self, tmp_path):
        import gzip
        import os as _os

        import numpy as np

        from bsseqconsensusreads_tpu.io import native

        if not native.available():
            import pytest

            pytest.skip(native.load_error())
        rng = np.random.default_rng(8)
        # compressible-but-not-trivial payload spanning many blocks
        payload = rng.integers(0, 16, size=1_500_000, dtype=np.uint8).tobytes()
        p1 = str(tmp_path / "st.bgzf")
        pn = str(tmp_path / "mt.bgzf")
        w = native.NativeBgzfWriter(p1, threads=1)
        for off in range(0, len(payload), 77_777):
            w.write(payload[off : off + 77_777])
        w.close()
        w = native.NativeBgzfWriter(pn, threads=6)
        for off in range(0, len(payload), 33_333):
            w.write(payload[off : off + 33_333])
        w.close()
        a = open(p1, "rb").read()
        b = open(pn, "rb").read()
        assert a == b
        with gzip.open(pn, "rb") as fh:
            assert fh.read() == payload
        # tiny file: single short block + EOF marker
        pt = str(tmp_path / "tiny.bgzf")
        w = native.NativeBgzfWriter(pt, threads=6)
        w.write(b"hello")
        w.close()
        with gzip.open(pt, "rb") as fh:
            assert fh.read() == b"hello"
        assert _os.path.getsize(pt) > 28  # EOF block present


class TestStaleLibraryFallback:
    def test_missing_symbols_degrade_gracefully(self, tmp_path, monkeypatch):
        """A stale .so lacking newly added symbols must load as unavailable
        (with a reason), never raise AttributeError out of the binding code
        (round-2 advisor finding)."""
        import subprocess
        import sys

        from bsseqconsensusreads_tpu.io import _nativelib

        src = tmp_path / "dummy.cpp"
        src.write_text('extern "C" int bamio_open() { return 0; }\n')
        so = tmp_path / "libdummy.so"
        try:
            subprocess.run(
                ["g++", "-shared", "-fPIC", "-o", str(so), str(src)],
                check=True, capture_output=True, timeout=60,
            )
        except Exception:
            pytest.skip("no g++ available")
        monkeypatch.setattr(_nativelib, "NATIVE_DIR", str(tmp_path))
        lib, err = _nativelib.load_library(
            "libdummy.so", "dummy.cpp",
            required_symbols=("bamio_open", "bamio_new_entry_point"),
        )
        assert lib is None
        assert "bamio_new_entry_point" in (err or "")
        # the stale .so was removed so the (failed) rebuild can't be skipped
        assert not so.exists()

    def test_symbol_check_passes_on_complete_library(
        self, tmp_path, monkeypatch
    ):
        import subprocess

        from bsseqconsensusreads_tpu.io import _nativelib

        src = tmp_path / "dummy2.cpp"
        src.write_text('extern "C" int f_one() { return 1; }\n')
        so = tmp_path / "libdummy2.so"
        try:
            subprocess.run(
                ["g++", "-shared", "-fPIC", "-o", str(so), str(src)],
                check=True, capture_output=True, timeout=60,
            )
        except Exception:
            pytest.skip("no g++ available")
        monkeypatch.setattr(_nativelib, "NATIVE_DIR", str(tmp_path))
        lib, err = _nativelib.load_library(
            "libdummy2.so", "dummy2.cpp", required_symbols=("f_one",)
        )
        assert err is None and lib is not None
        assert lib.f_one() == 1


@pytest.mark.slow
def test_mt_writer_clean_under_tsan(tmp_path):
    """The multi-threaded BGZF writer's queue/backpressure protocol under
    ThreadSanitizer: a TSan build of bamio.cpp drives 25 MB through 4
    workers and must produce zero data-race reports (SURVEY.md §5.2:
    threaded C++ gets sanitizer coverage)."""
    import subprocess
    import sys

    from bsseqconsensusreads_tpu.io._nativelib import NATIVE_DIR

    src = os.path.join(NATIVE_DIR, "bamio.cpp")
    so = str(tmp_path / "libbamio_tsan.so")
    try:
        subprocess.run(
            ["g++", "-O1", "-g", "-fPIC", "-fsanitize=thread", "-pthread",
             "-std=c++17", "-shared", "-o", so, src, "-lz"],
            check=True, capture_output=True, timeout=180,
        )
        tsan_rt = subprocess.run(
            ["g++", "-print-file-name=libtsan.so.2"],
            capture_output=True, text=True, timeout=30,
        ).stdout.strip()
    except Exception as e:
        pytest.skip(f"no TSan toolchain: {e}")
    if not os.path.isabs(tsan_rt):
        pytest.skip("libtsan runtime not found")
    driver = tmp_path / "drive.py"
    driver.write_text(
        "import ctypes as C, random\n"
        f"lib = C.CDLL({so!r})\n"
        "lib.bamio_create_mt.restype = C.c_void_p\n"
        "lib.bamio_create_mt.argtypes = [C.c_char_p, C.c_int, C.c_int, C.c_char_p, C.c_int]\n"
        "lib.bamio_write_mt.restype = C.c_int\n"
        "lib.bamio_write_mt.argtypes = [C.c_void_p, C.c_void_p, C.c_int64]\n"
        "lib.bamio_finish_mt.restype = C.c_int\n"
        "lib.bamio_finish_mt.argtypes = [C.c_void_p]\n"
        "err = C.create_string_buffer(256)\n"
        f"h = lib.bamio_create_mt({str(tmp_path / 'o.bgzf').encode()!r}, 6, 4, err, 256)\n"
        "assert h, err.value\n"
        "random.seed(0)\n"
        "payload = bytes(random.getrandbits(8) for _ in range(1 << 16))\n"
        "for _ in range(400):\n"
        "    assert lib.bamio_write_mt(h, payload, len(payload)) == 0\n"
        "assert lib.bamio_finish_mt(h) == 0\n"
    )
    env = dict(os.environ, LD_PRELOAD=tsan_rt,
               TSAN_OPTIONS="halt_on_error=0 exitcode=66")
    cp = subprocess.run(
        [sys.executable, str(driver)],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert cp.returncode == 0, cp.stderr[-2000:]
    assert "WARNING: ThreadSanitizer" not in cp.stderr, cp.stderr[-3000:]
    # output must still be a valid BGZF stream
    import gzip

    with gzip.open(tmp_path / "o.bgzf", "rb") as fh:
        assert len(fh.read()) == 400 * (1 << 16)

    # ---- read side: the parallel-inflate pipeline over the same file ----
    reader = tmp_path / "drive_read.py"
    reader.write_text(
        "import ctypes as C\n"
        f"lib = C.CDLL({so!r})\n"
        "lib.bamio_open_mt.restype = C.c_void_p\n"
        "lib.bamio_open_mt.argtypes = [C.c_char_p, C.c_int, C.c_char_p, C.c_int]\n"
        "lib.bamio_read.restype = C.c_int64\n"
        "lib.bamio_read.argtypes = [C.c_void_p, C.c_void_p, C.c_int64]\n"
        "lib.bamio_close.argtypes = [C.c_void_p]\n"
        "err = C.create_string_buffer(256)\n"
        f"h = lib.bamio_open_mt({str(tmp_path / 'o.bgzf').encode()!r}, 4, err, 256)\n"
        "assert h, err.value\n"
        "buf = C.create_string_buffer(1 << 20)\n"
        "total = 0\n"
        "while True:\n"
        "    got = lib.bamio_read(h, buf, 1 << 20)\n"
        "    assert got >= 0\n"
        "    if got == 0:\n"
        "        break\n"
        "    total += got\n"
        "lib.bamio_close(h)\n"
        f"assert total == 400 * (1 << 16), total\n"
    )
    cp = subprocess.run(
        [sys.executable, str(reader)],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert cp.returncode == 0, cp.stderr[-2000:]
    assert "WARNING: ThreadSanitizer" not in cp.stderr, cp.stderr[-3000:]
