"""Banded intra-family aligner (ops.banded) vs a brute-force scalar oracle.

The reference drops indel reads outright (tools/1.convert_AG_to_CT.py:79-80);
this op is above-parity, so its contract is defined here: same recurrence as
the scalar DP, correct window projection for match/insert/delete paths, and
a refuse-to-align gate for garbage.
"""

import numpy as np
import pytest

from bsseqconsensusreads_tpu.alphabet import BASE_CODE, NBASE
from bsseqconsensusreads_tpu.ops.banded import banded_align, banded_scores

MATCH, MISMATCH, GAP, BS = 4.0, -6.0, -8.0, 1.0


def codes(s):
    return BASE_CODE[np.frombuffer(s.encode(), dtype=np.uint8)].astype(np.int8)


def oracle_best_score(read, ref, off, band):
    """Scalar banded NW: same recurrence, python loops."""
    width = 2 * band + 1
    NEGI = -1e9

    def sub(x, r):
        if x == NBASE or r == NBASE:
            return 0.0
        if x == r:
            return MATCH
        if (x, r) in ((3, 1), (0, 2)):  # T over C, A over G
            return BS
        return MISMATCH

    l = len(read)
    w = len(ref)
    m = [[GAP * abs(d - band) for d in range(width)]]
    for i in range(1, l + 1):
        x = read[i - 1]
        if x == NBASE:
            m.append(list(m[i - 1]))
            continue
        pre = []
        for d in range(width):
            col = off + (i - 1) + (d - band)
            diag = m[i - 1][d] + (sub(x, ref[col]) if 0 <= col < w else NEGI)
            up = (m[i - 1][d + 1] + GAP) if d + 1 < width else NEGI
            pre.append(max(diag, up))
        row = [NEGI] * width
        for d in range(width):
            for k in range(d + 1):
                row[d] = max(row[d], pre[k] + GAP * (d - k))
        m.append(row)
    return max(m[l])


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_scores_match_oracle(seed):
    rng = np.random.default_rng(seed)
    band, w, l = 4, 48, 20
    n = 6
    reads = rng.integers(0, 4, size=(n, l)).astype(np.int8)
    reads[0, 15:] = NBASE  # short read with trailing pad
    reads[1, 7] = NBASE  # mid-read N
    ref = rng.integers(0, 4, size=(n, w)).astype(np.int8)
    offsets = rng.integers(2, 10, size=n).astype(np.int32)
    m = np.asarray(banded_scores(reads, ref, offsets, band, MATCH, MISMATCH, GAP, BS))
    for i in range(n):
        want = oracle_best_score(list(reads[i]), list(ref[i]), int(offsets[i]), band)
        got = m[i, l].max()
        assert got == pytest.approx(want), f"read {i}"


def test_exact_read_places_at_offset():
    anchor = codes("ACGTACGTACGTACGTACGT")
    ref = np.full(32, NBASE, np.int8)
    ref[4:24] = anchor
    read = np.full((1, 20), NBASE, np.int8)
    read[0] = anchor
    quals = np.full((1, 20), 30, np.uint8)
    b, q, ok = banded_align(read, quals, ref[None], np.array([4], np.int32), band=4)
    assert ok[0]
    np.testing.assert_array_equal(b[0, 4:24], anchor)
    assert (b[0, :4] == NBASE).all() and (b[0, 24:] == NBASE).all()
    assert (q[0, 4:24] == 30).all()


def test_deletion_read_shifts_right():
    """Read missing anchor base 10: chars after it land one column right."""
    anchor = codes("ACGTTGCAACGTTGCAACGT")
    ref = np.full(32, NBASE, np.int8)
    ref[4:24] = anchor
    read_seq = np.concatenate([anchor[:10], anchor[11:]])  # 19 chars
    read = np.full((1, 19), NBASE, np.int8)
    read[0] = read_seq
    quals = np.full((1, 19), 30, np.uint8)
    b, q, ok = banded_align(read, quals, ref[None], np.array([4], np.int32), band=4)
    assert ok[0]
    np.testing.assert_array_equal(b[0, 4:14], anchor[:10])
    assert b[0, 14] == NBASE  # deleted column: no observation
    np.testing.assert_array_equal(b[0, 15:24], anchor[11:])


def test_insertion_read_drops_inserted_char():
    anchor = codes("ACGTTGCAACGTTGCAACGT")
    ref = np.full(32, NBASE, np.int8)
    ref[4:24] = anchor
    read_seq = np.concatenate([anchor[:10], [NBASE - 1], anchor[10:]])  # 21 chars, insert 'T'
    read_seq[10] = 3  # T inserted
    read = np.full((1, 21), NBASE, np.int8)
    read[0] = read_seq
    quals = np.full((1, 21), 30, np.uint8)
    b, q, ok = banded_align(read, quals, ref[None], np.array([4], np.int32), band=4)
    assert ok[0]
    np.testing.assert_array_equal(b[0, 4:24], anchor)  # insertion vanished


def test_bisulfite_lenient_t_over_c():
    anchor = codes("ACCCCACCCCACCCCACCCC")
    ref = np.full(28, NBASE, np.int8)
    ref[2:22] = anchor
    read_seq = anchor.copy()
    read_seq[anchor == 1] = 3  # every C read as T (full conversion)
    read = read_seq[None].astype(np.int8)
    quals = np.full((1, 20), 30, np.uint8)
    b, _, ok = banded_align(read, quals, ref[None], np.array([2], np.int32), band=3)
    assert ok[0]
    np.testing.assert_array_equal(b[0, 2:22], read_seq)


def test_garbage_read_refused():
    rng = np.random.default_rng(9)
    ref = rng.integers(0, 4, size=(1, 40)).astype(np.int8)
    read = ((ref[0, 5:25] + 2) % 4)[None].astype(np.int8)  # all mismatches
    quals = np.full((1, 20), 30, np.uint8)
    _, _, ok = banded_align(
        read, quals, ref, np.array([5], np.int32), band=4, min_score_per_base=1.0
    )
    assert not ok[0]


def test_encode_align_policy_recovers_indel_read():
    """End-to-end through the encoder: with indel_policy='drop' (parity) an
    indel read contributes nothing; with 'align' it adds depth everywhere
    except the deleted column."""
    from bsseqconsensusreads_tpu.io.bam import BamRecord, CDEL, CMATCH
    from bsseqconsensusreads_tpu.ops.encode import codes_to_seq, encode_molecular_families

    rng = np.random.default_rng(3)
    frag = rng.integers(0, 4, size=40).astype(np.int8)
    seq = codes_to_seq(frag)
    qual = bytes([30] * 40)

    def rec(qname, s, cigar, pos=100):
        return BamRecord(
            qname=qname, flag=0x1 | 0x40, ref_id=0, pos=pos,
            cigar=cigar, seq=s, qual=bytes([30] * len(s)),
            tags={"MI": ("Z", "7/A"), "RX": ("Z", "AA-CC")},
        )

    normal = [rec(f"t{i}", seq, [(CMATCH, 40)]) for i in range(2)]
    # third template: deletion of base 20 (19M 1D 20M)
    del_seq = codes_to_seq(np.concatenate([frag[:19], frag[20:]]))
    indel = rec("t2", del_seq, [(CMATCH, 19), (CDEL, 1), (CMATCH, 20)])

    fam = [("7", normal + [indel])]
    drop_batch, _ = encode_molecular_families(fam, indel_policy="drop")
    align_batch, _ = encode_molecular_families(fam, indel_policy="align")
    assert drop_batch.indel_aligned == 0
    assert align_batch.indel_aligned == 1 and align_batch.indel_dropped == 0

    def depth(batch):
        return (batch.bases[0, :, 0, :] != NBASE).sum(axis=0)

    d_drop, d_align = depth(drop_batch), depth(align_batch)
    assert d_drop[:40].max() == 2
    # recovered read adds depth on matched columns, none on the deleted one
    assert (d_align[:19] == 3).all()
    assert d_align[19] == 2
    assert (d_align[20:40] == 3).all()
    # and the recovered bases agree with the fragment
    row = align_batch.bases[0, 2, 0]
    np.testing.assert_array_equal(row[:19], frag[:19])
    assert row[19] == NBASE
    np.testing.assert_array_equal(row[20:40], frag[20:])


def test_mid_read_n_skipped_not_placed():
    anchor = codes("ACGTACGTACGTACGTACGT")
    ref = np.full(30, NBASE, np.int8)
    ref[3:23] = anchor
    read_seq = anchor.copy()
    read_seq[7] = NBASE
    read = read_seq[None].astype(np.int8)
    quals = np.full((1, 20), 30, np.uint8)
    b, _, ok = banded_align(read, quals, ref[None], np.array([3], np.int32), band=3)
    assert ok[0]
    assert b[0, 10] == NBASE  # the N char's column stays unobserved
    np.testing.assert_array_equal(b[0, 3:10], anchor[:7])
    np.testing.assert_array_equal(b[0, 11:23], anchor[8:])
