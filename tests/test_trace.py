"""grafttrace tests: cross-process causal tracing end to end.

* mint/propagate/stamp — trace contexts minted at admission, bound
  thread-locally, stamped onto ordinary ledger lines, and emitted as
  completed parent-linked spans; unarmed/untraced paths stay one branch;
* ledger round-trips — a real inline router + 2-replica serve run and a
  real coordinator + worker run over tcp each leave a ledger from which
  `trace_tools.assemble` rebuilds COMPLETE causal trees: zero orphan
  spans, every job/slice trace terminal, counters reconciled;
* critical path — arithmetic on a hand-built span forest: root→leaf
  walk, bucket ranking, orphan detection, requeue annotation;
* flight recorder — bounded ring, SIGUSR1 dump, dump-on-demand;
* metrics plane — the `metrics` protocol op on serve and coordinator
  servers, `cli observe top`, and the transport's typed refusals for
  oversized/garbage metrics traffic;
* byte identity — arming the tracing plane changes no output bytes;
* truncation smoke — `cli observe trace` exits non-zero when a ledger
  of the set is missing (the tier-1 gate bench.py's trace leg rides).
"""

import dataclasses
import hashlib
import json
import os
import signal
import struct
import threading
import time

import numpy as np
import pytest

from bsseqconsensusreads_tpu import cli
from bsseqconsensusreads_tpu.config import FrameworkConfig
from bsseqconsensusreads_tpu.elastic import (
    Coordinator,
    SliceLedger,
    run_elastic,
    split_input,
    worker as worker_mod,
)
from bsseqconsensusreads_tpu.elastic.coordinator import (
    ENV_COORDINATOR_ADDR,
    ENV_WORKER_ID,
)
from bsseqconsensusreads_tpu.elastic.coordinator import config_doc
from bsseqconsensusreads_tpu.io.bam import BamWriter
from bsseqconsensusreads_tpu.serve import transport
from bsseqconsensusreads_tpu.serve.router import Router
from bsseqconsensusreads_tpu.serve.server import ServeEngine, ServeServer
from bsseqconsensusreads_tpu.utils import observe, trace_tools
from bsseqconsensusreads_tpu.utils.testing import (
    make_grouped_bam_records,
    random_genome,
    write_fasta,
)


@pytest.fixture(autouse=True)
def _fresh_observe():
    """Sinks, the flight ring, and the lazy proc trace are process
    globals; reset between tests so each starts unarmed and empty."""
    yield
    observe.close_sinks()
    observe._FLIGHT = None
    observe._PROC_TRACE = None


def _lines(path):
    return [json.loads(s) for s in open(path).read().splitlines()]


def _sha(path: str) -> str:
    with open(path, "rb") as fh:
        return hashlib.sha256(fh.read()).hexdigest()


# ---------------------------------------------------------------------------
# mint / propagate / stamp


class TestMintAndStamp:
    def test_mint_emits_zero_duration_root_span(self, tmp_path, monkeypatch):
        sink = str(tmp_path / "l.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        ctx = observe.mint_trace("job", "j0001")
        assert set(ctx) == {"trace", "span"}
        assert ctx["trace"].startswith("job-j0001-")
        (d,) = _lines(sink)
        assert d["event"] == "span" and d["name"] == "job_admit"
        assert d["trace"] == ctx["trace"] and d["span"] == ctx["span"]
        assert "parent" not in d  # a root resolves every later child
        assert d["t0"] == d["t1"] and d["dur_s"] == 0.0

    def test_trace_kind(self):
        assert observe.trace_kind("job-j0001-a1b2c3") == "job"
        assert observe.trace_kind("slice-s0002-ffffff") == "slice"
        assert observe.trace_kind("proc-pid77-0") == "proc"

    def test_bind_trace_stamps_ordinary_events(self, tmp_path, monkeypatch):
        sink = str(tmp_path / "l.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        ctx = observe.mint_trace("job", "j0002")
        with observe.bind_trace(ctx):
            observe.emit("inside", {"k": 1})
            assert observe.current_trace() == ctx
        observe.emit("outside", {"k": 2})
        assert observe.current_trace() is None
        by_event = {d["event"]: d for d in _lines(sink) if d["event"] != "span"}
        assert by_event["inside"]["trace"] == ctx["trace"]
        assert by_event["inside"]["span"] == ctx["span"]
        assert "trace" not in by_event["outside"]

    def test_bind_trace_malformed_yields_none(self):
        for bogus in (None, "job-x-1", {}, {"trace": "t"}, {"span": "s"}, 7):
            with observe.bind_trace(bogus) as bound:
                assert bound is None
            assert observe.current_trace() is None

    def test_bind_trace_restores_previous_binding(self):
        outer = {"trace": "job-a-000000", "span": "1.1"}
        inner = {"trace": "slice-b-000000", "span": "1.2"}
        with observe.bind_trace(outer):
            with observe.bind_trace(inner):
                assert observe.current_trace()["trace"] == inner["trace"]
            assert observe.current_trace()["trace"] == outer["trace"]

    def test_nested_spans_chain_parents(self, tmp_path, monkeypatch):
        sink = str(tmp_path / "l.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        ctx = observe.mint_trace("slice", "s0000")
        with observe.bind_trace(ctx):
            with observe.span("outer") as outer:
                with observe.span("inner") as inner:
                    assert inner["trace"] == ctx["trace"]
        spans = {d["name"]: d for d in _lines(sink)}
        assert spans["inner"]["parent"] == outer["span"]
        assert spans["outer"]["parent"] == ctx["span"]
        # the file round-trips into a whole single-trace forest
        report = trace_tools.assemble(sink)
        assert report.by_kind() == {"slice": 1}
        assert report.orphans == []

    def test_span_without_context_is_noop(self, tmp_path, monkeypatch):
        sink = str(tmp_path / "l.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        with observe.span("nothing") as s:
            assert s is None
        assert not os.path.exists(sink)  # nothing was ever emitted

    def test_emit_span_external_window(self, tmp_path, monkeypatch):
        sink = str(tmp_path / "l.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        ctx = observe.mint_trace("proc", "pidX")
        sid = observe.emit_span("worker_spawn", 10.0, 12.5, ctx=ctx, rid="r0")
        assert isinstance(sid, str)
        d = _lines(sink)[-1]
        assert d["name"] == "worker_spawn" and d["parent"] == ctx["span"]
        assert d["dur_s"] == pytest.approx(2.5)
        assert d["rid"] == "r0"
        assert observe.emit_span("x", 0.0, 1.0) is None  # no ctx in scope

    def test_span_ids_unique_and_process_scoped(self):
        ids = {observe._next_span_id() for _ in range(100)}
        assert len(ids) == 100
        assert all(i.startswith(f"{os.getpid():x}.") for i in ids)

    def test_unarmed_emit_is_one_branch_and_rings_nothing(self, monkeypatch):
        monkeypatch.delenv("BSSEQ_TPU_STATS", raising=False)
        observe._FLIGHT = None
        observe.emit("tick", {"i": 1})
        # the early return fired before record build OR ring append
        assert observe._FLIGHT is None


# ---------------------------------------------------------------------------
# flight recorder


class TestFlightRecorder:
    def test_ring_is_bounded_and_keeps_latest(self, tmp_path, monkeypatch):
        sink = str(tmp_path / "l.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        monkeypatch.setenv("BSSEQ_TPU_FLIGHT_RING", "8")
        observe._FLIGHT = None  # cap is read at first ring build
        for i in range(50):
            observe.emit("tick", {"i": i})
        assert observe.flight_dump("test") == 8
        d = _lines(sink)[-1]
        assert d["event"] == "flight_record" and d["reason"] == "test"
        assert d["count"] == 8
        assert [e["i"] for e in d["events"]] == list(range(42, 50))

    def test_dump_excludes_prior_dumps_from_ring(self, tmp_path, monkeypatch):
        sink = str(tmp_path / "l.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        observe._FLIGHT = None
        observe.emit("tick", {"i": 0})
        assert observe.flight_dump("first") == 1
        # the flight_record line itself never re-enters the ring
        assert observe.flight_dump("second") == 1
        events = [d["event"] for d in _lines(sink)]
        assert events.count("flight_record") == 2

    def test_empty_ring_dump_is_zero(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BSSEQ_TPU_STATS", str(tmp_path / "l.jsonl"))
        observe._FLIGHT = None
        assert observe.flight_dump("empty") == 0

    def test_sigusr1_dumps_ring(self, tmp_path, monkeypatch):
        sink = str(tmp_path / "l.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        observe._FLIGHT = None
        prev = signal.getsignal(signal.SIGUSR1)
        try:
            observe.install_flight_signal()
            observe.emit("alive", {"n": 1})
            os.kill(os.getpid(), signal.SIGUSR1)
            deadline = time.monotonic() + 5.0
            dumped = []
            while time.monotonic() < deadline and not dumped:
                dumped = [
                    d for d in _lines(sink)
                    if d["event"] == "flight_record"
                ]
                time.sleep(0.01)
        finally:
            signal.signal(signal.SIGUSR1, prev)
        assert dumped and dumped[0]["reason"] == "sigusr1"
        assert dumped[0]["count"] == 1
        assert dumped[0]["events"][0]["event"] == "alive"


# ---------------------------------------------------------------------------
# critical-path arithmetic on a hand-built forest


def _span_line(trace, sid, name, t0, t1, parent=None, **extra):
    d = {
        "ts": t0, "event": "span", "name": name, "trace": trace,
        "span": sid, "t0": t0, "t1": t1, "dur_s": round(t1 - t0, 6),
    }
    if parent is not None:
        d["parent"] = parent
    d.update(extra)
    return d


def _write_ledger(path, lines):
    with open(path, "w") as fh:
        for d in lines:
            fh.write(json.dumps(d) + "\n")


class TestHandBuiltForest:
    TID = "job-j0001-abcdef"

    def forest(self):
        """root(0s) -> ingest(100..104) -> retire(101..103.5)
                    -> transport(100.5..102)
        latest-finishing span is `ingest` (a non-leaf beats its child):
        the critical path is the chain root > ingest."""
        return [
            _span_line(self.TID, "1.1", "job_admit", 100.0, 100.0),
            _span_line(self.TID, "1.2", "ingest", 100.0, 104.0,
                       parent="1.1"),
            _span_line(self.TID, "1.3", "transport", 100.5, 102.0,
                       parent="1.1"),
            _span_line(self.TID, "1.4", "chunk_retire", 101.0, 103.5,
                       parent="1.2"),
            {"ts": 104.0, "event": "job_complete", "trace": self.TID,
             "span": "1.1", "job": "j0001"},
        ]

    def test_critical_path_walks_to_root(self, tmp_path):
        path = str(tmp_path / "l.jsonl")
        _write_ledger(path, self.forest())
        report = trace_tools.assemble(path)
        trace = report.traces[self.TID]
        assert [s.name for s in trace.critical_path()] == [
            "job_admit", "ingest"
        ]
        assert trace.terminal() and not trace.requeued()
        assert trace.t0 == 100.0 and trace.t1 == 104.0
        assert trace_tools.check_traces(report) == []

    def test_buckets_ranked_by_total_duration(self, tmp_path):
        path = str(tmp_path / "l.jsonl")
        _write_ledger(path, self.forest())
        report = trace_tools.assemble(path)
        buckets = report.buckets()
        assert [b[0] for b in buckets] == [
            "ingest", "chunk_retire", "transport", "job_admit"
        ]
        assert buckets[0][2] == pytest.approx(4.0)
        assert buckets[-1] == ("job_admit", 1, 0.0)

    def test_orphan_detection_and_exit_code(self, tmp_path, capsys):
        lines = self.forest()
        lines.append(
            _span_line(self.TID, "1.9", "lost_child", 102.0, 103.0,
                       parent="9.9")
        )
        path = str(tmp_path / "l.jsonl")
        _write_ledger(path, lines)
        report = trace_tools.assemble(path)
        assert report.orphans == [(self.TID, "1.9", "9.9", "lost_child")]
        problems = trace_tools.check_traces(report)
        assert any("orphan span 1.9" in p for p in problems)
        assert cli.main(["observe", "trace", path]) == 1
        assert "orphan" in capsys.readouterr().err

    def test_nonterminal_requeued_trace_is_annotated(self, tmp_path):
        tid = "slice-s0001-ffffff"
        lines = [
            _span_line(tid, "2.1", "slice_admit", 10.0, 10.0),
            _span_line(tid, "2.2", "slice_pipeline", 10.0, 11.0,
                       parent="2.1"),
            {"ts": 11.5, "event": "slice_requeued", "trace": tid,
             "span": "2.1", "sid": 1},
        ]
        path = str(tmp_path / "l.jsonl")
        _write_ledger(path, lines)
        report = trace_tools.assemble(path)
        assert report.traces[tid].requeued()
        (problem,) = trace_tools.check_traces(report)
        assert "never reached a terminal state" in problem
        assert "(requeued, then lost)" in problem

    def test_proc_traces_are_terminal_exempt(self, tmp_path):
        tid = "proc-pid123-0"
        path = str(tmp_path / "l.jsonl")
        _write_ledger(path, [
            _span_line(tid, "3.1", "proc_admit", 5.0, 5.0),
            _span_line(tid, "3.2", "jax_import", 5.0, 9.0, parent="3.1"),
        ])
        report = trace_tools.assemble(path)
        assert trace_tools.check_traces(report) == []

    def test_longest_trace_wins_critical_path(self, tmp_path):
        other = "job-j0002-123456"
        lines = self.forest() + [
            _span_line(other, "4.1", "job_admit", 200.0, 200.0),
            _span_line(other, "4.2", "ingest", 200.0, 210.0, parent="4.1"),
            {"ts": 210.0, "event": "job_complete", "trace": other,
             "span": "4.1", "job": "j0002"},
        ]
        path = str(tmp_path / "l.jsonl")
        _write_ledger(path, lines)
        report = trace_tools.assemble(path)
        assert report.longest().tid == other  # 10s wall beats 4s

    def test_reconcile_flags_unadmitted_job_trace(self, tmp_path):
        lines = self.forest()
        # admitted under its trace...
        lines.append({"ts": 100.0, "event": "job_admitted",
                      "trace": self.TID, "span": "1.1", "job": "j0001"})
        # ...plus a routed trace that never reached any replica
        ghost = "job-f0009-dddddd"
        lines.append(_span_line(ghost, "5.1", "job_admit", 300.0, 300.0))
        path = str(tmp_path / "l.jsonl")
        _write_ledger(path, lines)
        problems = trace_tools.check_traces(trace_tools.assemble(path))
        assert any("no admission event" in p and ghost in p
                   for p in problems)
        assert any(ghost in p and "terminal" in p for p in problems)

    def test_reconcile_flags_untraced_admission(self, tmp_path):
        lines = self.forest()
        lines.append({"ts": 100.0, "event": "job_admitted", "job": "jX"})
        path = str(tmp_path / "l.jsonl")
        _write_ledger(path, lines)
        problems = trace_tools.check_traces(trace_tools.assemble(path))
        assert any("carry no trace id" in p for p in problems)

    def test_reconcile_flags_untraced_route(self, tmp_path):
        lines = self.forest()
        lines.append({"ts": 100.0, "event": "fleet_route", "job": "f0001",
                      "replica": "r0"})
        path = str(tmp_path / "l.jsonl")
        _write_ledger(path, lines)
        problems = trace_tools.check_traces(trace_tools.assemble(path))
        assert any("fleet_route" in p and "no trace id" in p
                   for p in problems)

    def test_requeued_reroute_same_trace_reconciles(self, tmp_path):
        """A killed replica's job is RE-routed under the same trace:
        two stamped fleet_route events, one requeue, one terminal —
        placements outnumber traces and that is fine."""
        lines = self.forest()
        for ts in (100.0, 102.0):
            lines.append({"ts": ts, "event": "fleet_route",
                          "trace": self.TID, "span": "1.1",
                          "job": "f0001"})
        lines.append({"ts": 101.5, "event": "fleet_requeue",
                      "trace": self.TID, "span": "1.1", "job": "f0001"})
        lines.append({"ts": 100.3, "event": "job_admitted",
                      "trace": self.TID, "span": "1.1", "job": "j0001"})
        path = str(tmp_path / "l.jsonl")
        _write_ledger(path, lines)
        report = trace_tools.assemble(path)
        assert trace_tools.check_traces(report) == []
        assert report.traces[self.TID].requeued()

    def test_reconcile_flags_split_vs_slice_traces(self, tmp_path):
        tid = "slice-s0000-aaaaaa"
        lines = [
            _span_line(tid, "6.1", "slice_admit", 1.0, 1.0),
            {"ts": 1.0, "event": "elastic_split", "slices": 3,
             "records": 10, "trace": tid, "span": "6.1"},
            {"ts": 2.0, "event": "elastic_slice_done", "trace": tid,
             "span": "6.1", "sid": 0},
        ]
        path = str(tmp_path / "l.jsonl")
        _write_ledger(path, lines)
        problems = trace_tools.check_traces(trace_tools.assemble(path))
        assert any("split produced 3 slices but 1 slice traces" in p
                   for p in problems)

    def test_truncated_ledger_set_fails_whole_set_passes(
        self, tmp_path, capsys
    ):
        """The tier-1 truncation smoke: drop one ledger of a two-file
        set whose root spans live in the dropped file — `observe trace`
        must exit non-zero on the orphaned remainder."""
        rundir = str(tmp_path / "run")
        os.makedirs(rundir)
        _write_ledger(os.path.join(rundir, "router.jsonl"), [
            _span_line(self.TID, "1.1", "job_admit", 100.0, 100.0),
            _span_line(self.TID, "1.5", "transport", 100.0, 100.2,
                       parent="1.1", op="submit"),
        ])
        _write_ledger(os.path.join(rundir, "replica.jsonl"), [
            _span_line(self.TID, "7.1", "ingest", 100.2, 103.0,
                       parent="1.1"),
            {"ts": 103.0, "event": "job_complete", "trace": self.TID,
             "span": "7.1", "job": "j0001"},
        ])
        assert cli.main(["observe", "trace", rundir]) == 0
        out = capsys.readouterr().out
        assert "orphans: 0" in out and "overhead buckets" in out
        os.unlink(os.path.join(rundir, "router.jsonl"))
        assert cli.main(["observe", "trace", rundir]) == 1
        err = capsys.readouterr().err
        assert "orphan" in err
        # `observe check` on the directory fails the same way
        assert cli.main(["observe", "check", rundir]) == 1
        capsys.readouterr()


# ---------------------------------------------------------------------------
# live metrics plane: protocol op + `observe top` + typed refusals


class _Replica:
    """Fleet-protocol shim pointing at a real in-thread ServeServer."""

    def __init__(self, rid, address):
        self.rid = rid
        self.address = address
        self.proc = None
        self.generation = 0

    @property
    def supervised(self) -> bool:
        return False

    def alive(self) -> bool:
        return True


class _Fleet:
    def __init__(self, replicas):
        self.replicas = list(replicas)

    def alive(self):
        return list(self.replicas)

    def lookup(self, rid):
        for r in self.replicas:
            if r.rid == rid:
                return r
        return None

    def restart(self, replica):
        pass


def _start_server(server):
    # graftlint: owned-thread -- test accept loop, drained in teardown
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    deadline = time.monotonic() + 10.0
    while not server.bound and time.monotonic() < deadline:
        time.sleep(0.01)
    assert server.bound
    return thread


class TestMetricsPlane:
    @pytest.fixture()
    def served_engine(self):
        eng = ServeEngine(batch_families=4, stride=2)
        eng.start()
        server = ServeServer(eng, addresses=["tcp:127.0.0.1:0"])
        thread = _start_server(server)
        yield server.bound[0], eng
        server.request_drain()
        thread.join(timeout=10.0)
        eng.stop(timeout=30)

    def test_serve_metrics_op(self, served_engine):
        addr, _eng = served_engine
        resp = transport.request(addr, {"op": "metrics"}, timeout=10.0)
        assert resp["ok"]
        m = resp["metrics"]
        assert m["component"] == "serve"
        assert m["queue_depth"] == 0 and m["engine_alive"] is True
        for key in ("uptime_s", "jobs_by_state", "chip_busy",
                    "batches_shared_jobs_rate", "counters"):
            assert key in m, key

    def test_coordinator_metrics_op(self, tmp_path):
        rundir = str(tmp_path / "run")
        os.makedirs(os.path.join(rundir, "slices"), exist_ok=True)
        specs = [{"sid": 0, "path": "slices/s0.bam", "records": 1,
                  "families": 1, "family_crc": 7, "input_crc": 0}]
        ledger = SliceLedger(rundir, specs, lease_s=30.0)
        server = Coordinator(
            ledger, {"doc": True}, addresses=["tcp:127.0.0.1:0"]
        )
        thread = _start_server(server)
        try:
            ledger.join("w0")
            ledger.lease("w0")
            resp = transport.request(
                server.bound[0], {"op": "metrics"}, timeout=10.0
            )
        finally:
            server.request_drain()
            thread.join(timeout=10.0)
        assert resp["ok"]
        m = resp["metrics"]
        assert m["component"] == "coordinator"
        assert m["slices"] == 1 and m["outstanding_leases"] == 1
        assert m["lease_backlog"] == 0 and m["workers"] == 1
        assert m["counters"] == {
            "requeues": 0, "workers_lost": 0, "preempts": 0,
        }

    def test_observe_top_polls_json_lines(self, served_engine, capsys):
        addr, _eng = served_engine
        rc = cli.main([
            "observe", "top", "--address", addr,
            "--count", "2", "--interval", "0.01",
        ])
        assert rc == 0
        lines = capsys.readouterr().out.splitlines()
        assert len(lines) == 2
        for line in lines:
            sample = json.loads(line)
            assert sample["component"] == "serve"
            assert "queue_depth" in sample

    def test_observe_top_dead_address_exits_nonzero(self, capsys):
        rc = cli.main([
            "observe", "top", "--address", "tcp:127.0.0.1:1", "--count", "1",
        ])
        assert rc == 1
        capsys.readouterr()

    def test_oversized_metrics_request_typed_refusal(self, served_engine):
        addr, _eng = served_engine
        huge = {"op": "metrics", "pad": "x" * (transport.MAX_FRAME + 1)}
        with pytest.raises(transport.TransportError):
            transport.request(addr, huge, timeout=10.0)

    def test_garbage_metrics_frame_answered_with_guard(self, served_engine):
        addr, _eng = served_engine
        sock, kind = transport.connect(addr, timeout=5.0)
        try:
            body = b"metrics please"
            sock.sendall(struct.pack("!I", len(body)) + body)
            resp = transport.recv_message(sock, kind)
        finally:
            sock.close()
        assert resp["ok"] is False and resp["guard"] == "bad_json"


# ---------------------------------------------------------------------------
# ledger round-trips: real runs, whole causal trees


GENOME = "".join(
    "ACGT"[i] for i in np.random.default_rng(7).integers(0, 4, size=2000)
)


def _grouped_bam(path, seed, n_families=4):
    header, records = make_grouped_bam_records(
        np.random.default_rng(seed), f"chr{seed % 97}", GENOME,
        n_families=n_families, reads_per_strand=(2, 2), read_len=40,
    )
    with BamWriter(path, header) as w:
        w.write_all(records)


@pytest.fixture(scope="module")
def elastic_input(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("trace_elastic")
    rng = np.random.default_rng(906)
    name, genome = random_genome(rng, 4000)
    fasta = str(tmp / "genome.fa")
    write_fasta(fasta, name, genome)
    header, records = make_grouped_bam_records(
        rng, name, genome, n_families=6, error_rate=0.01
    )
    bam = str(tmp / "in.bam")
    with BamWriter(bam, header) as w:
        w.write_all(records)
    cfg = FrameworkConfig(
        genome_dir=os.path.dirname(fasta),
        genome_fasta_file_name=os.path.basename(fasta),
        aligner="self",
    )
    return {"bam": bam, "cfg": cfg, "tmp": tmp}


class TestLedgerRoundTrips:
    def test_serve_router_two_replicas_zero_orphans(
        self, tmp_path, monkeypatch
    ):
        """Inline router + 2 real replicas over tcp: both job traces are
        minted at the router, ride the `_trace` wire field into replica
        admission, and close with replica-side job_complete — one whole
        tree per job, zero orphans, counters reconciled."""
        sink = str(tmp_path / "fleet.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        engines, servers, threads = [], [], []
        for _ in range(2):
            eng = ServeEngine(batch_families=4, stride=2)
            eng.start()
            srv = ServeServer(eng, addresses=["tcp:127.0.0.1:0"])
            threads.append(_start_server(srv))
            engines.append(eng)
            servers.append(srv)
        fleet = _Fleet([
            _Replica(f"r{i}", srv.bound[0])
            for i, srv in enumerate(servers)
        ])
        router = Router(replicas=fleet)  # no launch(): no monitor thread
        try:
            for k in range(2):
                inp = str(tmp_path / f"in{k}.bam")
                _grouped_bam(inp, seed=300 + k)
                resp = router.submit({
                    "input": inp, "output": str(tmp_path / f"out{k}.bam"),
                })
                assert resp["ok"], resp
            placed = {j.replica_id for j in router._jobs.values()}
            assert placed == {"r0", "r1"}  # least-outstanding spread
            for eng in engines:
                for job in eng.queue.jobs():
                    st = eng.wait(job.id, timeout=120)
                    assert st["state"] == "done", st
        finally:
            for srv, thread in zip(servers, threads):
                srv.request_drain()
                thread.join(timeout=10.0)
            for eng in engines:
                eng.stop(timeout=30)
        observe.close_sinks()
        report = trace_tools.assemble(sink)
        assert trace_tools.check_traces(report) == []
        assert report.by_kind().get("job") == 2
        assert report.orphans == []
        for trace in report.traces.values():
            if trace.kind != "job":
                continue
            assert trace.terminal()
            names = {s.name for s in trace.spans.values()}
            assert "job_admit" in names  # the router-side mint
            assert "transport" in names  # the forward leg, same tree
            events = {e.get("event") for e in trace.events}
            assert "fleet_route" in events and "job_admitted" in events
            assert "job_complete" in events
        # the CLI agrees end to end
        assert cli.main(["observe", "trace", sink]) == 0

    def test_coordinator_worker_over_tcp_zero_orphans(
        self, elastic_input, tmp_path, monkeypatch
    ):
        """Real coordinator + work_loop over tcp: slice traces minted at
        split, shipped inside lease grants, closed by the coordinator's
        commit — every slice one whole tree across both endpoints."""
        sink = str(tmp_path / "elastic.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        monkeypatch.setenv(ENV_WORKER_ID, "wt0")
        monkeypatch.setenv(ENV_COORDINATOR_ADDR, "")
        rundir = str(tmp_path / "run")
        os.makedirs(rundir, exist_ok=True)
        cfg = elastic_input["cfg"]
        specs = split_input(elastic_input["bam"], rundir, 2)
        assert all("trace" in sl for sl in specs)
        ledger = SliceLedger(rundir, specs, lease_s=30.0)
        server = Coordinator(
            ledger, config_doc(cfg), addresses=["tcp:127.0.0.1:0"]
        )
        thread = _start_server(server)
        try:
            processed = worker_mod.work_loop(
                server.bound[0], worker_id="wt0"
            )
        finally:
            server.request_drain()
            thread.join(timeout=10.0)
        assert processed == 2
        observe.close_sinks()
        report = trace_tools.assemble(sink)
        assert trace_tools.check_traces(report) == []
        assert report.by_kind().get("slice") == 2
        for trace in report.traces.values():
            if trace.kind != "slice":
                continue
            assert trace.terminal()
            names = {s.name for s in trace.spans.values()}
            assert "slice_pipeline" in names
            events = {e.get("event") for e in trace.events}
            assert "elastic_slice_done" in events
        assert cli.main(["observe", "trace", sink]) == 0

    def test_elastic_inline_run_round_trips(
        self, elastic_input, tmp_path, monkeypatch
    ):
        """The merged artifact path: run_elastic inline over 3 slices
        leaves a ledger whose forest `observe check` passes whole, and
        trace_summary carries the bucket table for HEAD artifacts."""
        sink = str(tmp_path / "inline.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        outdir = str(tmp_path / "out")
        _target, rep = run_elastic(
            elastic_input["cfg"], elastic_input["bam"], outdir,
            inline=True, slices=3,
        )
        assert rep["ok"]
        observe.close_sinks()
        report = trace_tools.assemble(sink)
        assert trace_tools.check_traces(report) == []
        assert report.by_kind().get("slice") == 3
        summary = trace_tools.trace_summary(sink)
        assert summary["ok"] and summary["orphans"] == 0
        assert summary["traces"]["slice"] == 3
        assert "slice_pipeline" in summary["buckets"]
        assert "merge" in summary["buckets"]
        assert summary["critical_path"]["spans"]

    def test_tracing_changes_no_output_bytes(self, tmp_path, monkeypatch):
        """Byte-identity pin: the same input through `cli molecular`
        with the ledger armed and unarmed produces identical BAMs."""
        inp = str(tmp_path / "in.bam")
        _grouped_bam(inp, seed=42)
        quiet = str(tmp_path / "quiet.bam")
        traced = str(tmp_path / "traced.bam")
        monkeypatch.delenv("BSSEQ_TPU_STATS", raising=False)
        assert cli.main([
            "molecular", "-i", inp, "-o", quiet,
            "--batching", "sequential",
        ]) == 0
        sink = str(tmp_path / "l.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        assert cli.main([
            "molecular", "-i", inp, "-o", traced,
            "--batching", "sequential",
        ]) == 0
        observe.close_sinks()
        assert _sha(traced) == _sha(quiet)
        assert os.path.exists(sink)  # the traced run really was armed
