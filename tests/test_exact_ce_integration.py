"""End-to-end exact-ce integration: raw grouped reads -> molecular ->
duplex, then every duplex ce column re-derived INDEPENDENTLY from the raw
observations.

The unit tests pin the exact-ce formula on hand-built families; this test
pins the whole chain on a random corpus — placement registers, the cB tag
round trip through real BAM records, the strand/role row mapping, and the
conversion context — by mapping EVERY raw observation's base through the
strand read's conversion context and counting mismatches with the duplex
call directly (a per-column scalar recomputation structured nothing like
the production plane/scatter pass; shared building blocks are only the
pinned twins: _overlap_cocall_np and hosttwin.convert_cell).

Boundary columns (conversion prepend, extend-gap copies, trailing trim)
use documented halo rules and are excluded: assertions cover the interior
of each strand's raw span.
"""

from __future__ import annotations

import numpy as np
import pytest

from bsseqconsensusreads_tpu.alphabet import NBASE
from bsseqconsensusreads_tpu.models.molecular import _overlap_cocall_np
from bsseqconsensusreads_tpu.models.params import ConsensusParams
from bsseqconsensusreads_tpu.ops.hosttwin import convert_cell
from bsseqconsensusreads_tpu.pipeline.calling import (
    StageStats,
    call_duplex_batches,
    call_molecular_batches,
)
from bsseqconsensusreads_tpu.utils.testing import (
    make_grouped_bam_records,
    random_genome,
)

#: duplex-input flag -> (family strand suffix, merged role, is_convert_row)
_FLAG_INFO = {99: ("A", 0, False), 163: ("B", 0, True),
              83: ("B", 1, True), 147: ("A", 1, False)}


@pytest.fixture(scope="module")
def pipeline_outputs():
    rng = np.random.default_rng(91)
    name, genome = random_genome(rng, 20000)
    _header, raw = make_grouped_bam_records(
        rng, name, genome, n_families=16, reads_per_strand=(1, 4),
        read_len=60, error_rate=0.06,
    )
    params = ConsensusParams(min_reads=1)
    mol = []
    for batch in call_molecular_batches(
        iter(list(raw)), params=params, mode="self", batch_families=5,
        grouping="coordinate", stats=StageStats(), mesh=None,
    ):
        mol.extend(batch)
    dup = []
    for batch in call_duplex_batches(
        iter([r.copy() for r in mol]), lambda n, s, e: genome[s:e], [name],
        params=ConsensusParams(min_reads=0), mode="self", batch_families=6,
        grouping="coordinate", stats=StageStats(), mesh=None,
    ):
        dup.extend(batch)
    return genome, raw, mol, dup, params


def _cocalled_family_obs(raw, fam, strand, params):
    """All observations of one strand family after the R1/R2 overlap
    co-call, keyed by (role, refcol) -> list of base codes."""
    # collect per template: role0 read + role1 read, co-call the overlap
    templates: dict = {}
    for rec in raw:
        if str(rec.get_tag("MI")) != f"{fam}/{strand}":
            continue
        info = _FLAG_INFO.get(rec.flag)
        if info is None or info[0] != strand:
            continue
        templates.setdefault(rec.qname, {})[info[1]] = rec
    out: dict = {}
    for qname, pair in templates.items():
        if len(pair) != 2:
            continue
        lo = min(r.pos for r in pair.values())
        hi = max(r.pos + len(r.seq) for r in pair.values())
        w = hi - lo
        b = np.full((1, 2, w), NBASE, np.int8)
        q = np.zeros((1, 2, w), np.int16)
        for role, rec in pair.items():
            s = rec.pos - lo
            b[0, role, s : s + len(rec.seq)] = [
                "ACGTN".index(c) for c in rec.seq
            ]
            q[0, role, s : s + len(rec.seq)] = np.frombuffer(
                rec.qual, np.uint8
            )
        if params.consensus_call_overlapping_bases:
            b, q = _overlap_cocall_np(b, q)
        observed = (b != NBASE) & (q >= params.min_input_base_quality)
        for role in range(2):
            for j in range(w):
                if observed[0, role, j]:
                    out.setdefault((role, lo + j), []).append(
                        int(b[0, role, j])
                    )
    return out


class TestExactCeEndToEnd:
    def test_duplex_ce_matches_raw_recomputation(self, pipeline_outputs):
        genome, raw, mol, dup, params = pipeline_outputs
        gcodes = np.asarray(["ACGTN".index(c) for c in genome], np.int8)
        # strand-consensus (molecular) records by (fam, strand, role):
        # their seq is the strand read the duplex stage transforms
        mol_by = {}
        for rec in mol:
            info = _FLAG_INFO.get(rec.flag)
            if info is None:
                continue
            fam = str(rec.get_tag("MI")).split("/")[0]
            mol_by[(fam, info[0], info[1])] = rec
        checked = 0
        expect: dict = {}  # id(duplex rec) -> {col_index: expected ce}
        for rec in dup:
            fam = str(rec.get_tag("MI"))
            role = 1 if rec.flag & 0x80 else 0
            _s, cd = rec.get_tag("cd")
            _s, ce = rec.get_tag("ce")
            for strand in ("A", "B"):
                srec = mol_by.get((fam, strand, role))
                if srec is None:
                    continue
                obs = _cocalled_family_obs(raw, fam, strand, params)
                convert_row = strand == "B"
                # interior columns of the strand's raw span only
                # (boundary columns use documented halo rules)
                for i in range(2, len(rec.seq) - 2):
                    col = rec.pos + i
                    key_obs = obs.get((role, col))
                    if key_obs is None:
                        continue
                    if rec.seq[i] == "N":
                        continue
                    j = col - srec.pos
                    if not (0 <= j < len(srec.seq) - 1):
                        continue
                    call = "ACGTN".index(rec.seq[i])
                    # conversion context of the strand consensus read
                    nxt = np.int8("ACGTN".index(srec.seq[j + 1]))
                    mapped = [
                        int(
                            convert_cell(
                                np.int8(x), np.bool_(convert_row),
                                gcodes[col], gcodes[col + 1], nxt,
                                np.bool_(True),
                            )
                        )
                        for x in key_obs
                    ]
                    want_err = sum(1 for m in mapped if m != call)
                    # the OTHER strand contributes the rest of ce[i]:
                    # accumulate both strands before comparing
                    checked += 1
                    cols = expect.setdefault(id(rec), {})
                    cols[i] = cols.get(i, 0) + want_err
        assert checked > 200
        mismatches = []
        for rec in dup:
            exp = expect.get(id(rec))
            if not exp:
                continue
            _s, cd = rec.get_tag("cd")
            _s, ce = rec.get_tag("ce")
            fam = str(rec.get_tag("MI"))
            role = 1 if rec.flag & 0x80 else 0
            for i, want in exp.items():
                # only compare when BOTH strands were recomputed (a
                # missing strand keeps its production value)
                n_strands = sum(
                    1
                    for s in ("A", "B")
                    if (fam, s, role) in
                    {(str(m.get_tag("MI")).split("/")[0],
                      _FLAG_INFO[m.flag][0], _FLAG_INFO[m.flag][1])
                     for m in mol if m.flag in _FLAG_INFO}
                )
                if n_strands != 2:
                    continue
                if int(ce[i]) != want:
                    mismatches.append((fam, role, i, int(ce[i]), want))
        assert not mismatches, mismatches[:10]
