"""The run-ledger subsystem (utils.observe + utils.ledger_tools + the
`observe` CLI): thread-safe span accumulation, the single locked writer,
run manifests, phase classification, the ledger-closure invariant over a
mini end-to-end pipeline, and the stray-stderr lint guard."""

import json
import os
import threading
import time

import numpy as np
import pytest

from bsseqconsensusreads_tpu.utils import ledger_tools, observe

PKG = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "bsseqconsensusreads_tpu",
)


@pytest.fixture(autouse=True)
def _fresh_sinks():
    """Writers are registered per sink path for the process lifetime;
    close between tests so tmp files release and manifests re-open."""
    yield
    observe.close_sinks()


# ---------------------------------------------------------------------------
# Metrics: concurrent + nested span accumulation.


class TestMetricsConcurrency:
    def test_add_seconds_exact_under_contention(self):
        """The locked read-modify-write (shared by timed/add_seconds via
        _accumulate) must lose no update: 8 threads x 5000 adds of 1 ms
        sum to exactly 40 s."""
        m = observe.Metrics()

        def worker():
            for _ in range(5000):
                m.add_seconds("x", 0.001)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert m.seconds["x"] == pytest.approx(40.0)
        assert m.spans["x"][1] == 40_000

    def test_timed_from_four_worker_threads_no_lost_seconds(self):
        """The overlap-engine usage pattern: >=4 threads timing the same
        phase concurrently with the main thread. Accumulated seconds must
        be at least the sum of every thread's sleeps (no lost updates)."""
        m = observe.Metrics()
        per_thread, reps, naps = 4, 5, 0.002

        def worker():
            for _ in range(reps):
                with m.timed("kernel"):
                    time.sleep(naps)

        threads = [threading.Thread(target=worker) for _ in range(per_thread)]
        for t in threads:
            t.start()
        with m.timed("ingest"):
            time.sleep(naps)
        for t in threads:
            t.join()
        assert m.seconds["kernel"] >= per_thread * reps * naps
        assert m.spans["kernel"][1] == per_thread * reps
        assert m.seconds["ingest"] >= naps

    def test_counters_concurrent(self):
        m = observe.Metrics()

        def worker():
            for _ in range(10_000):
                m.count("records")

        threads = [threading.Thread(target=worker) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert m.counters["records"] == 60_000


class TestSpanTree:
    def test_nested_and_threaded_entry(self):
        """Nested spans record slash paths per thread; a worker's span
        roots its own tree (its stack is thread-local) and owner_seconds
        counts only the owning thread's OUTERMOST spans — the closure
        denominator must not double-count nesting or workers."""
        m = observe.Metrics()
        with m.timed("emit"):
            with m.timed("sort_write"):
                time.sleep(0.001)

        def worker():
            with m.timed("kernel"):
                with m.timed("fetch"):
                    time.sleep(0.001)

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        assert set(m.spans) == {
            "emit", "emit/sort_write", "kernel", "kernel/fetch"
        }
        tree = m.span_tree()
        assert "sort_write" in tree["emit"]["children"]
        assert "fetch" in tree["kernel"]["children"]
        # child wall is contained in the parent's
        assert (
            tree["emit"]["children"]["sort_write"]["seconds"]
            <= tree["emit"]["seconds"]
        )
        # closure denominator: owner thread's outermost spans only
        assert set(m.owner_seconds) == {"emit"}

    def test_phase_summary_classification(self):
        m = observe.Metrics()
        m.add_seconds("ingest", 1.0)
        m.add_seconds("encode", 0.5)
        m.add_seconds("kernel", 2.0)
        m.add_seconds("device_wait", 0.5)
        m.add_seconds("fetch", 0.5)
        m.add_seconds("stall", 0.25)
        p = m.phase_summary(wall=5.0)
        assert p["host_s"] == pytest.approx(1.5)
        assert p["device_s"] == pytest.approx(3.0)
        assert p["stall_s"] == pytest.approx(0.25)
        assert p["chip_busy"] == pytest.approx(3.0 / 5.0)
        # everything above was owner-thread outermost: attributed
        assert p["unattributed_s"] == pytest.approx(5.0 - 4.75)

    def test_stage_stats_report_phase_block(self):
        from bsseqconsensusreads_tpu.pipeline.calling import StageStats

        st = StageStats(stage="molecular")
        st.wall_seconds = 2.0
        st.metrics.add_seconds("kernel", 1.0)
        st.metrics.add_seconds("emit", 0.5)
        d = st.as_dict()
        for key in ("host_s", "device_s", "stall_s", "chip_busy",
                    "unattributed_s"):
            assert key in d
        assert d["chip_busy"] == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# The locked ledger writer + manifest.


class TestLedgerWriter:
    def test_concurrent_emits_interleave_whole_lines(self, tmp_path,
                                                     monkeypatch):
        sink = str(tmp_path / "ledger.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        n_threads, n_lines = 8, 200

        def worker(tid):
            for i in range(n_lines):
                observe.emit("tick", {"tid": tid, "i": i})

        threads = [
            threading.Thread(target=worker, args=(t,))
            for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        lines = open(sink).read().splitlines()
        assert len(lines) == n_threads * n_lines
        seen = set()
        for line in lines:
            d = json.loads(line)  # every line parses: no torn writes
            assert d["event"] == "tick"
            assert "thread" in d  # worker-thread emits are attributed
            seen.add((d["tid"], d["i"]))
        assert len(seen) == n_threads * n_lines  # no lost lines

    def test_lines_survive_without_explicit_flush(self, tmp_path,
                                                  monkeypatch):
        """Every line is flushed as written: a hard crash loses at most
        the in-flight line (the crash-resume pairing)."""
        sink = str(tmp_path / "ledger.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        observe.emit("alive", {"n": 1})
        # read back while the writer still holds the handle open
        assert json.loads(open(sink).read())["n"] == 1

    def test_manifest_opens_ledger_once(self, tmp_path, monkeypatch):
        sink = str(tmp_path / "ledger.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        assert observe.open_ledger(config_digest="abc123", component="test")
        observe.open_ledger(component="test")  # re-entrant: one manifest
        observe.emit("x", {})
        lines = [json.loads(s) for s in open(sink).read().splitlines()]
        assert [d["event"] for d in lines] == ["run_manifest", "x"]
        man = lines[0]
        assert man["config_digest"] == "abc123"
        assert man["git_rev"] and man["version"]
        assert "backend" in man and "device_count" in man and "env" in man

    def test_open_ledger_disabled_is_silent(self, monkeypatch):
        monkeypatch.delenv("BSSEQ_TPU_STATS", raising=False)
        assert observe.open_ledger(component="test") is False

    def test_digest_matches_file_content(self, tmp_path, monkeypatch):
        import hashlib

        sink = str(tmp_path / "ledger.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        observe.open_ledger(component="test", query_devices=False)
        observe.emit("x", {"v": 1})
        digest = observe.ledger_digest()
        assert digest == hashlib.sha256(open(sink, "rb").read()).hexdigest()

    def test_config_digest_stable(self):
        from bsseqconsensusreads_tpu.config import FrameworkConfig

        a = observe.config_digest(FrameworkConfig())
        b = observe.config_digest(FrameworkConfig())
        c = observe.config_digest(FrameworkConfig(batch_families=9))
        assert a == b != c


# ---------------------------------------------------------------------------
# Overlap-pool disable visibility (VERDICT weak #6).


class TestOverlapPoolEvents:
    def test_multi_device_paths_emit_disable_event(self, tmp_path,
                                                   monkeypatch):
        from bsseqconsensusreads_tpu.pipeline.calling import (
            StageStats,
            _make_overlap_pool,
        )

        sink = str(tmp_path / "ledger.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        stats = StageStats(stage="molecular")
        pool, depth = _make_overlap_pool(
            object(), None, stats, "molecular"
        )
        assert pool is None and depth == 0
        assert stats.metrics.counters["overlap_pool_disabled"] == 1
        d = json.loads(open(sink).read().splitlines()[-1])
        assert d["event"] == "overlap_pool_disabled"
        assert d["stage"] == "molecular"
        assert "round-robin" in d["reason"]
        # the counter rides the stage's stats line too
        assert stats.as_dict()["overlap_pool_disabled"] == 1

    def test_host_backend_disable_reason(self, tmp_path, monkeypatch):
        from bsseqconsensusreads_tpu.pipeline.calling import (
            StageStats,
            _make_overlap_pool,
        )

        sink = str(tmp_path / "ledger.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        monkeypatch.delenv("BSSEQ_TPU_OVERLAP_THREADS", raising=False)
        stats = StageStats(stage="duplex")
        pool, _ = _make_overlap_pool(None, None, stats, "duplex")
        assert pool is None  # tests run on the cpu backend
        d = json.loads(open(sink).read().splitlines()[-1])
        assert d["reason"].startswith("host backend")

    def test_explicit_disable_reason(self, tmp_path, monkeypatch):
        from bsseqconsensusreads_tpu.pipeline.calling import (
            StageStats,
            _make_overlap_pool,
        )

        sink = str(tmp_path / "ledger.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        monkeypatch.setenv("BSSEQ_TPU_OVERLAP_THREADS", "0")
        stats = StageStats()
        _make_overlap_pool(None, None, stats, "molecular")
        d = json.loads(open(sink).read().splitlines()[-1])
        assert "BSSEQ_TPU_OVERLAP_THREADS" in d["reason"]


class TestHeartbeat:
    def test_beat_emits_sequenced_events(self, tmp_path, monkeypatch):
        from bsseqconsensusreads_tpu.parallel.multihost import WorkerHeartbeat

        sink = str(tmp_path / "ledger.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        hb = WorkerHeartbeat(component="test")
        hb.beat("init")
        hb.beat("batch_assembled", families=128)
        lines = [json.loads(s) for s in open(sink).read().splitlines()]
        assert [d["seq"] for d in lines] == [1, 2]
        assert lines[1]["phase"] == "batch_assembled"
        assert lines[1]["families"] == 128
        assert all(d["event"] == "worker_heartbeat" for d in lines)

    def test_periodic_thread_start_stop(self, tmp_path, monkeypatch):
        from bsseqconsensusreads_tpu.parallel.multihost import WorkerHeartbeat

        sink = str(tmp_path / "ledger.jsonl")
        monkeypatch.setenv("BSSEQ_TPU_STATS", sink)
        hb = WorkerHeartbeat(component="test")
        hb.start(interval_s=0.01)
        time.sleep(0.08)
        hb.stop()
        lines = open(sink).read().splitlines()
        assert len(lines) >= 2
        assert all(
            json.loads(s)["phase"] == "alive" for s in lines
        )


# ---------------------------------------------------------------------------
# Ledger tools + the observe CLI over a mini end-to-end pipeline run.


@pytest.fixture(scope="module")
def mini_pipeline_ledger(tmp_path_factory):
    """A real (tiny) self-aligned pipeline run with the stats sink on —
    the in-tree twin of the SCALECPU round artifacts. Asserting the
    closure invariant here pins it at every future HEAD."""
    from bsseqconsensusreads_tpu.config import FrameworkConfig
    from bsseqconsensusreads_tpu.io.bam import BamWriter
    from bsseqconsensusreads_tpu.pipeline.stages import run_pipeline
    from bsseqconsensusreads_tpu.utils.testing import (
        make_grouped_bam_records,
        random_genome,
        write_fasta,
    )

    tmp = tmp_path_factory.mktemp("observe_pipe")
    rng = np.random.default_rng(77)
    name, genome = random_genome(rng, 6000)
    fasta = str(tmp / "genome.fa")
    write_fasta(fasta, name, genome)
    header, records = make_grouped_bam_records(
        rng, name, genome, n_families=8, error_rate=0.0
    )
    bam = str(tmp / "input" / "mini.bam")
    os.makedirs(os.path.dirname(bam), exist_ok=True)
    with BamWriter(bam, header) as w:
        w.write_all(records)
    sink = str(tmp / "ledger.jsonl")
    os.environ["BSSEQ_TPU_STATS"] = sink
    try:
        cfg = FrameworkConfig(
            genome_dir=str(tmp), genome_fasta_file_name="genome.fa",
            tmp=str(tmp), aligner="self", backend="cpu", batch_families=4,
        )
        run_pipeline(cfg, bam, outdir=str(tmp / "out"))
    finally:
        os.environ.pop("BSSEQ_TPU_STATS", None)
        observe.close_sinks()
    return sink


class TestLedgerClosure:
    def test_ledger_opens_with_manifest(self, mini_pipeline_ledger):
        first = json.loads(open(mini_pipeline_ledger).readline())
        assert first["event"] == "run_manifest"
        assert first["component"] == "pipeline"
        assert first["backend"] == "cpu"

    def test_rule_phase_sums_close_to_pipeline_wall(
        self, mini_pipeline_ledger
    ):
        """THE ledger-closure invariant, asserted in-tree: per-rule wall
        seconds sum to pipeline_s, and each stage's owner-thread timeline
        is attributed to phases, within tolerance."""
        s = ledger_tools.summarize_ledger(mini_pipeline_ledger)
        assert s.problems == []
        assert s.pipeline["pipeline_s"] > 0
        rule_sum = sum(r["seconds"] for r in s.rules)
        assert rule_sum == pytest.approx(
            s.pipeline["pipeline_s"],
            abs=ledger_tools.CLOSURE_ABS_TOL,
            rel=ledger_tools.CLOSURE_REL_TOL,
        )

    def test_stage_lines_carry_phase_report(self, mini_pipeline_ledger):
        s = ledger_tools.summarize_ledger(mini_pipeline_ledger)
        assert set(s.stages) == {"molecular", "duplex"}
        for st in s.stages.values():
            for key in ("host_s", "device_s", "stall_s", "chip_busy",
                        "unattributed_s", "wall_seconds"):
                assert key in st
            # cpu backend, overlap off: the device share is the inline
            # kernel+fetch wall, host share must dominate
            assert st["wall_seconds"] > 0

    def test_overlap_disable_is_visible_in_ledger(
        self, mini_pipeline_ledger
    ):
        """VERDICT weak #6: the cpu-backend run must SAY the overlap pool
        was off, in both the event stream and the stage counters."""
        s = ledger_tools.summarize_ledger(mini_pipeline_ledger)
        assert s.events.get("overlap_pool_disabled", 0) >= 2
        assert any("overlap pool disabled" in n for n in s.notes)
        for st in s.stages.values():
            assert st.get("overlap_pool_disabled", 0) >= 1

    def test_cli_summarize_prints_table_and_passes(
        self, mini_pipeline_ledger, capsys
    ):
        from bsseqconsensusreads_tpu import cli

        rc = cli.main(["observe", "summarize", mini_pipeline_ledger])
        out = capsys.readouterr().out
        assert rc == 0
        assert "chip_busy" in out and "molecular" in out and "duplex" in out
        assert "pipeline_s" in out
        assert "ledger OK" in out

    def test_cli_check_smoke_every_line_schema_valid(
        self, mini_pipeline_ledger, capsys
    ):
        """The CI smoke: `observe check` over a real mini-pipeline ledger
        schema-validates every JSONL line and the closure invariant."""
        from bsseqconsensusreads_tpu import cli

        rc = cli.main(["observe", "check", mini_pipeline_ledger])
        assert rc == 0
        assert json.loads(capsys.readouterr().out)["ok"] is True

    def test_cli_check_rejects_corrupted_ledger(
        self, mini_pipeline_ledger, tmp_path, capsys
    ):
        from bsseqconsensusreads_tpu import cli

        bad = str(tmp_path / "bad.jsonl")
        content = open(mini_pipeline_ledger).read()
        open(bad, "w").write(content + "{truncated-not-json\n")
        assert cli.main(["observe", "check", bad]) == 1
        capsys.readouterr()
        # manifest-less ledger: also non-zero
        headless = str(tmp_path / "headless.jsonl")
        open(headless, "w").write(content.split("\n", 1)[1])
        assert cli.main(["observe", "check", headless]) == 1
        capsys.readouterr()
        # missing file: non-zero
        assert cli.main(["observe", "check", str(tmp_path / "nope")]) == 2

    def test_cli_check_rejects_broken_closure(self, tmp_path, capsys):
        from bsseqconsensusreads_tpu import cli

        bad = str(tmp_path / "gap.jsonl")
        with open(bad, "w") as fh:
            fh.write(json.dumps({
                "ts": 1.0, "event": "run_manifest", "git_rev": "x",
                "version": "0", "backend": "cpu", "device_count": 1,
            }) + "\n")
            fh.write(json.dumps({
                "ts": 2.0, "event": "rule_complete", "rule": "a",
                "seconds": 1.0, "ran": True,
            }) + "\n")
            fh.write(json.dumps({
                "ts": 3.0, "event": "pipeline_complete", "pipeline_s": 60.0,
            }) + "\n")
        assert cli.main(["observe", "check", bad]) == 1
        err = capsys.readouterr().err
        assert "closure" in err

    def test_cli_diff_two_ledgers(self, mini_pipeline_ledger, capsys):
        from bsseqconsensusreads_tpu import cli

        rc = cli.main([
            "observe", "diff", mini_pipeline_ledger, mini_pipeline_ledger
        ])
        out = capsys.readouterr().out
        assert rc == 0
        assert "B/A" in out
        assert "molecular.host_s" in out
        assert "1.00x" in out  # self-diff: identical


# ---------------------------------------------------------------------------
# Lint guard: diagnostics go through the ledger, summaries through
# observe.stderr_line — never bare stderr prints in package source.
# Migrated from the PR-1 regex scan to graftlint's AST checker (which
# also catches sys.stderr.write); this wrapper keeps the guard visible
# in the observability suite while tests/test_graftlint.py owns the
# engine coverage.


def test_no_bare_stderr_prints_outside_observe():
    from bsseqconsensusreads_tpu.analysis import run_lint

    offenders = [f.format() for f in run_lint([PKG], rules=["stderr-print"])]
    assert offenders == [], (
        "bare stderr prints in package source (route diagnostics through "
        f"the run ledger or observe.stderr_line): {offenders}"
    )
