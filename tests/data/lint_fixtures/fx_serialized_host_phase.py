"""graftlint fixture: serialized-host-phase — one seeded violation.

`hot_` prefix marks the loop as a batch-loop root; `host_workers` below
marks a host pool as available in the linted set. The rawize host span
runs inline between the batch's dispatch_kernel and fetch_out — the
serialized shape the rule flags. The post-fetch variant must stay
clean (that is the sanctioned worker-side retire shape).
"""


def host_workers():
    return 4


def fx_dispatch_kernel_stub(batch):
    return batch


def hot_serialized_batch_loop(batches, metrics, rawize, emit,
                              dispatch_kernel, fetch_out):
    out = []
    for batch in batches:
        wire = dispatch_kernel(batch)
        with metrics.timed("rawize"):  # seeded: serialized-host-phase
            rawize(batch)
        out.append(emit(fetch_out(wire)))
    return out


def hot_pipelined_batch_loop(batches, metrics, rawize, emit,
                             dispatch_kernel, fetch_out):
    """Clean twin: the host phases run AFTER the fetch, off the in-flight
    window — the worker-side retire shape."""
    out = []
    for batch in batches:
        wire = dispatch_kernel(batch)
        host = fetch_out(wire)
        with metrics.timed("rawize"):
            rawize(batch)
        with metrics.timed("emit"):
            out.append(emit(host))
    return out
