"""graftlint fixture: serial-deflate — one seeded violation.

`hot_` marks the function as a batch-loop root; 'merge' in its name
makes it a merge/emit root. The inline `zlib.compress` is the seeded
serial deflate on the merge thread (the r06 merge_bgzf wall shape). The
twin below writes through a codec-tier writer and must stay clean.
"""

import zlib


def hot_merge_runs(runs):
    out = []
    for payload in runs:
        out.append(zlib.compress(payload, 1))  # seeded: serial-deflate
    return out


def hot_merge_runs_codec(runs, writer):
    """Clean twin: bytes flow through a codec-tier writer (io.bam's
    _create_bgzf picks io.pbgzf.PBgzfWriter when workers exist) — the
    deflate fans out off the merge thread."""
    for payload in runs:
        writer.write(payload)
    writer.flush()
