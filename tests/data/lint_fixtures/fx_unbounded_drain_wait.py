"""graftlint fixture: unbounded-drain-wait — one seeded violation.

fx_drain_workers parks on a `.join()` with no timeout inside a drain
path: SIGKILL is the only way out if a worker wedges, which loses the
checkpoint flush the drain existed to protect. The bounded variant and
the identically-shaped wait OUTSIDE a drain-named function must stay
clean.
"""


def fx_drain_workers(threads):
    for t in threads:
        t.join()  # seeded: unbounded-drain-wait


def fx_drain_workers_bounded(threads, deadline):
    for t in threads:
        t.join(timeout=deadline)


def fx_feed_loop(queue):
    # an unbounded get on a worker feed path is NOT this rule's
    # business — blocking forever on new work is the design
    while True:
        item = queue.get()
        if item is None:
            return
