"""graftlint fixture: padded-batch-flops — one seeded violation.

`hot_` marks the batch-loop root. The seeded allocation densifies three
ragged dims (families x templates x window) to their batch maxima — the
[F, T, 2, W] envelope whose FLOPs scale with the worst family. The
packed twin below builds one dense row axis + segment ids (two ragged
dims at most per allocation) and must stay clean, as must the same
envelope in a non-hot report helper.
"""

import numpy as np


def hot_encode_batch(families, t_max, w_max):
    f = len(families)
    bases = np.full((f, t_max, 2, w_max), 5, np.int8)  # seeded: padded-batch-flops
    for fi, fam in enumerate(families):
        for ti, (codes, off) in enumerate(fam):
            bases[fi, ti, 0, off : off + len(codes)] = codes
    return bases


def hot_encode_batch_packed(families, w_max):
    """Clean twin: reads concatenate on one dense row axis; only the
    row bucket pads, and the window dim is shared — two ragged dims."""
    n_rows = sum(len(fam) for fam in families)
    rows = np.full((n_rows, 2, w_max), 5, np.int8)
    seg = np.repeat(
        np.arange(len(families), dtype=np.int32),
        [len(fam) for fam in families],
    )
    return rows, seg


def debug_envelope_report(families, t_max, w_max):
    """Same envelope shape off the hot path: a diagnostics helper may
    materialize it, the batch loop may not."""
    f = len(families)
    return np.zeros((f, t_max, 2, w_max), np.uint8)
