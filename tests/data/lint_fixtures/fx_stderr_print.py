"""graftlint fixture: stderr-print — one seeded violation."""

import sys


def fx_report(msg):
    print(msg, file=sys.stderr)  # seeded: stderr-print
