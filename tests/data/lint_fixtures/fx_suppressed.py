"""graftlint fixture: a correctly suppressed violation — must yield NO
findings, keeping the directory-wide fixture sweep at exactly one
finding per rule."""

import sys


def fx_quiet_report(msg):
    print(msg, file=sys.stderr)  # graftlint: disable=stderr-print -- fixture demonstrating inline suppression
