"""graftlint fixture: tracer-leak — one seeded violation.

Python `if` on a traced parameter inside a jitted function raises
TracerBoolConversionError at trace time.
"""

import jax


@jax.jit
def fx_traced_branch(x):
    if x > 0:  # seeded: tracer-leak
        return x
    return -x
