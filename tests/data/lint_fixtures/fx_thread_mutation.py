"""graftlint fixture: thread-unsafe-mutation — one seeded violation.

fx_worker runs as a Thread target and bumps a shared counter without
taking the lock the class even owns.
"""

import threading


class FxCounter:
    def __init__(self):
        self.n = 0
        self._lock = threading.Lock()

    def fx_worker(self):
        self.n += 1  # seeded: thread-unsafe-mutation


def fx_start(c: "FxCounter"):
    t = threading.Thread(target=c.fx_worker)
    t.start()
    return t
