"""Seeded fixture: unfenced-commit.

A worker loop that publishes a finished slice without any fence epoch
in scope — the zombie-writer shape graftnet's fencing refuses — plus a
clean twin that carries the lease grant's fence_epoch.
"""

from bsseqconsensusreads_tpu.serve import transport


def zombie_publish(address, sl, lease_id, manifest):
    slice_trace = sl.get("trace")  # traced, but STILL unfenced
    resp = transport.request(  # seeded: unfenced-commit
        address,
        {"op": "publish", "lease_id": lease_id,
         "slice": sl["sid"], "manifest": manifest,
         "trace": slice_trace},
        timeout=600.0,
    )
    return resp


def fenced_publish(address, sl, grant, manifest):
    # clean: the commit carries the grant's fence epoch, so a stale
    # holder is refused with publish_fenced instead of racing
    epoch = grant.get("fence_epoch")
    slice_trace = sl.get("trace")
    resp = transport.request(
        address,
        {"op": "publish", "lease_id": grant["lease_id"],
         "slice": sl["sid"], "manifest": manifest, "epoch": epoch,
         "trace": slice_trace},
        timeout=600.0,
    )
    return resp
