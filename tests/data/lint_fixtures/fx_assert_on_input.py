"""graftlint fixture: assert-on-input — one seeded violation.

hot_parse_record asserts on a field read from the record blob; under
`python -O` the check disappears and the corrupt length flows into the
slicing below. The typed-raise variant and the constant assert must
stay clean, and so must an assert outside any hot path or io/pipeline
module (this fixture lives under tests/data/, so only hot_-prefixed
functions are in scope here).
"""


def hot_parse_record(data):
    l_qname = data[8]
    assert l_qname >= 1, "corrupt qname length"  # seeded: assert-on-input
    return data[32 : 32 + l_qname]


def hot_parse_record_typed(data):
    l_qname = data[8]
    if l_qname < 1:
        raise ValueError("corrupt qname length")
    return data[32 : 32 + l_qname]


def hot_internal_invariant():
    table_built = True
    assert table_built  # bare name, no input taint: clean
    return table_built


def cold_parse_record(data):
    # same shape as the seed but not hot-reachable and not in an
    # io/pipeline module: out of scope
    assert data[8] >= 1
    return data
