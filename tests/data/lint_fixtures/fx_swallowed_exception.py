"""graftlint fixture: swallowed-exception — one seeded violation.

fx_worker_quiet runs as a Thread target; the except-pass means a failing
job dies with no trace anywhere.
"""

import threading


def fx_worker_quiet(jobs):
    for j in jobs:
        try:
            j()
        except Exception:  # seeded: swallowed-exception
            pass


def fx_spawn(jobs):
    return threading.Thread(target=fx_worker_quiet, args=(jobs,))
