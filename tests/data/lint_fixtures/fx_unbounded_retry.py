"""graftlint fixture: unbounded-retry — one seeded violation.

fx_read_forever spins on OSError with neither an attempt bound nor a
backoff; the bounded and backed-off variants below must stay clean.
"""

import time


def fx_read_forever(path):
    while True:
        try:
            with open(path) as fh:
                return fh.read()
        except OSError as exc:  # seeded: unbounded-retry
            last = exc
            del last


def fx_read_bounded(path):
    attempt = 0
    while True:
        try:
            with open(path) as fh:
                return fh.read()
        except OSError:
            attempt += 1
            if attempt >= 3:
                raise


def fx_read_backoff(path):
    while True:
        try:
            with open(path) as fh:
                return fh.read()
        except OSError:
            time.sleep(0.1)
