"""graftlint fixture: unframed-socket-read — one seeded violation.

fx_raw_tcp_reader trusts the peer for both the record boundary and the
size by calling raw ``conn.recv`` on a TCP connection; the delegating
variant below reads through the length-framed guarded transport reader
and must stay clean, as must the reviewed-and-suppressed frame pump.
"""

import socket


def fx_raw_tcp_reader(conn):
    data = conn.recv(1 << 20)  # seeded: unframed-socket-read
    return data.decode("utf-8", "replace")


def fx_framed_reader(transport, address, payload):
    conn = socket.create_connection(address, timeout=5.0)
    try:
        transport.send_message(conn, "tcp", payload)
        return transport.recv_message(conn, "tcp")
    finally:
        conn.close()


def fx_reviewed_frame_pump(conn, admitted_len):
    buf = bytearray()
    while len(buf) < admitted_len:
        # graftlint: disable=unframed-socket-read -- this IS the framed
        # reader: admitted_len was checked against MAX_FRAME upstream
        chunk = conn.recv(admitted_len - len(buf))
        if not chunk:
            break
        buf += chunk
    return bytes(buf)
