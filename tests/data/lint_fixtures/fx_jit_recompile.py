"""graftlint fixture: jit-recompile — one seeded violation.

jax.jit called inside the loop body builds a fresh callable (and compile
cache entry) per iteration.
"""

import jax


def fx_fresh_jits(xs):
    outs = []
    for x in xs:
        f = jax.jit(lambda v: v + 1)  # seeded: jit-recompile
        outs.append(f(x))
    return outs
