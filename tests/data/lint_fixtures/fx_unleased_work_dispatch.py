"""Seeded fixture: unleased-work-dispatch.

A dispatch loop handing work slices to a transport send with no lease
in scope, next to its leased twin that must stay clean.
"""

from bsseqconsensusreads_tpu.serve import transport


def dispatch_all(address, slices):
    results = []
    for sl in slices:
        slice_trace = sl.get("trace")  # traced, but STILL unleased
        resp = transport.request(address, {"op": "assign", "slice": sl, "trace": slice_trace})  # seeded: unleased-work-dispatch
        results.append(resp)
    return results


def dispatch_leased(address, slices, ledger):
    results = []
    for sl in slices:
        lease_id = ledger.lease(sl)
        lease_expires = ledger.expiry_of(lease_id)
        slice_trace = sl.get("trace")
        resp = transport.request(
            address,
            {"op": "assign", "slice": sl, "lease_id": lease_id,
             "until": lease_expires, "trace": slice_trace},
        )
        results.append(resp)
    return results
