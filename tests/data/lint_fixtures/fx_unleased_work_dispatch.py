"""Seeded fixture: unleased-work-dispatch.

A dispatch loop handing work slices to a transport send with no lease
in scope, next to its leased twin that must stay clean.
"""

from bsseqconsensusreads_tpu.serve import transport


def dispatch_all(address, slices):
    results = []
    for sl in slices:
        resp = transport.request(address, {"op": "assign", "slice": sl})  # seeded: unleased-work-dispatch
        results.append(resp)
    return results


def dispatch_leased(address, slices, ledger):
    results = []
    for sl in slices:
        lease_id = ledger.lease(sl)
        lease_expires = ledger.expiry_of(lease_id)
        resp = transport.request(
            address,
            {"op": "assign", "slice": sl, "lease_id": lease_id,
             "until": lease_expires},
        )
        results.append(resp)
    return results
