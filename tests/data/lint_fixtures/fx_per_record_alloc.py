"""graftlint fixture: per-record-alloc — one seeded violation.

`hot_` marks the function as a batch-loop root; 'emit' in its name makes
it an emit/sort root. The `.tolist()` inside the per-record loop is the
seeded per-record allocation (the r05 emit-wall shape). The columnar
twin below is the sanctioned batch-level shape and must stay clean.
"""


def hot_emit_batch(batch, depths):
    out = []
    for fi in range(len(batch)):
        cd = depths[fi].tolist()  # seeded: per-record-alloc
        out.append((fi, cd))
    return out


def hot_emit_batch_columnar(batch, depths):
    """Clean twin: tag arrays stay numpy, scalars precompute at batch
    level — what io.bam._encode_tags and _span_stats make possible."""
    totals = depths.sum(axis=-1)
    out = []
    for fi in range(len(batch)):
        out.append((fi, depths[fi], int(totals[fi])))
    return out
