"""graftlint fixture: blocking-scheduler-loop — one seeded violation.

fx_scheduler_spin parks its polling loop on time.sleep, which drain
and SIGTERM cannot preempt; the event-driven and bounded-queue
variants below must stay clean.
"""

import queue
import threading
import time

_stop = threading.Event()
_wake = threading.Event()


def fx_scheduler_spin(pending):
    while not _stop.is_set():
        if pending:
            pending.pop()
        time.sleep(0.05)  # seeded: blocking-scheduler-loop


def fx_scheduler_event_driven(pending):
    while not _stop.is_set():
        if pending:
            pending.pop()
        _wake.wait(timeout=0.05)
        _wake.clear()


def fx_retire_bounded_queue():
    q = queue.Queue(maxsize=8)
    drained = []
    while not _stop.is_set():
        if q.empty():
            break
        drained.append(q.get(timeout=0.25))
    return drained
