"""graftlint fixture: host-sync — one seeded violation.

`hot_` prefix marks the loop as a batch-loop root (engine.HOT_PATH_PREFIX);
`float(out)` forces a device->host sync per iteration with no accounted
ledger span around it.
"""

import jax


@jax.jit
def fx_kernel(x):
    return x * 2


def hot_fixture_loop(batches):
    total = 0.0
    for b in batches:
        out = fx_kernel(b)
        total += float(out)  # seeded: host-sync
    return total
