"""graftlint fixture: unfused-methyl-scan — one seeded violation.

`hot_` marks the batch-loop root; the function name carries the methyl
scope. The seeded loop re-derives per-site methylation evidence from
the consensus base planes one family at a time — the host-side scan
the fused kernel epilogue replaces. The vectorized twin below reduces
the same planes without a Python loop and must stay clean, as must an
identical loop outside methyl scope and a methyl-named helper off the
hot path.
"""

import numpy as np


def hot_methyl_scan_batch(planes, metas):
    meth = 0
    for i in range(len(metas)):
        row = planes[i]  # seeded: unfused-methyl-scan
        meth += int((row[1] & 0x0F).sum())
    return meth


def hot_methyl_reduce_batch(planes):
    """Clean twin: the same reduction vectorized over the family axis —
    no per-record Python interpretation of device-shaped data."""
    return int((planes[:, 1] & 0x0F).sum())


def hot_depth_histogram(planes, metas):
    """Same loop shape OUTSIDE methyl scope: a generic depth histogram
    over families is other rules' business."""
    depths = []
    for i in range(len(metas)):
        depths.append(int(planes[i, 0].sum()))
    return depths


def methyl_report_lines(planes, names):
    """Methyl-scoped but cold: a report helper off the batch loop may
    walk sites one at a time (the emit surface does)."""
    lines = []
    for i, name in enumerate(names):
        lines.append(f"{name}\t{int(planes[i, 1].sum())}")
    return lines
