"""graftlint fixture: io-in-device-span — one seeded violation.

A log write inside a `timed("device_wait")` block books host I/O as
chip/tunnel time.
"""


def fx_device_loop(metrics, fn, batches, log):
    out = None
    for b in batches:
        with metrics.timed("device_wait"):
            out = fn(b)
            log.write(str(out))  # seeded: io-in-device-span
    return out
