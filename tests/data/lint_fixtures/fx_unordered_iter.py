"""graftlint fixture: unordered-shape-iter — one seeded violation.

Iterating a set of sizes on a hot-path function (`hot_` prefix) makes
downstream batch shapes follow the hash seed.
"""


def hot_fixture_shapes(fn, items):
    sizes = {len(i) for i in items}
    outs = []
    for s in sizes:  # seeded: unordered-shape-iter
        outs.append(fn(s))
    return outs
