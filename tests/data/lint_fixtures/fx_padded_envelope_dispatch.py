"""graftlint fixture: padded-envelope-dispatch — one seeded violation.

`hot_` marks the batch-loop root. The seeded call hands the dense
[F, T, 2, W] envelope tensors to a sharded dispatcher even though the
batch's segment-packed plan is sitting right there — the route the
packed layout was built to kill. The clean twins dispatch the plan
itself (packed-aware callee), hand the envelope over where no plan
exists, or do it all off the hot path.
"""


def hot_dispatch_batch(batch, mesh, params):
    pk = batch.packed  # the segment-packed plan is available...
    if pk is None:
        return None
    return sharded_consensus(mesh, batch.bases, batch.quals, params)  # seeded: padded-envelope-dispatch


def hot_dispatch_batch_packed(batch, mesh, params):
    """Clean twin: the packed plan rides a packed-aware dispatcher."""
    pk = batch.packed
    return sharded_consensus_rows(mesh, pk.bases, pk.quals, pk.seg, params)


def hot_dispatch_legacy(batch, params):
    """Clean: no packed plan in scope — a stage that never built one may
    still ship the envelope (the padded layout's sanctioned route)."""
    return pack_wire_inputs(batch.bases, batch.quals, params)


def debug_replay_batch(batch, mesh, params):
    """Clean: same shape off the hot path — diagnostics may replay the
    envelope, the batch loop may not."""
    pk = batch.packed
    return sharded_consensus(mesh, batch.bases, batch.quals, params)
