"""Seeded fixture: untraced-transport-send.

A work payload (a job spec) handed to a transport send with no trace
context bound in the dispatching scope, next to its traced twin that
must stay clean. No dispatch loops here — the lease-discipline rule
(unleased-work-dispatch) is loop-scoped and owns its own fixture.
"""

from bsseqconsensusreads_tpu.serve import transport


def forward_job(address, spec):
    return transport.request(address, {"op": "submit", "spec": spec})  # seeded: untraced-transport-send


def forward_job_traced(address, spec, observe, job):
    with observe.bind_trace(job.trace) as trace_ctx:
        return transport.request(
            address, {"op": "submit", "spec": spec, "_trace": trace_ctx}
        )
