"""graftlint fixture: contract-drift — one seeded violation.

Emits a ledger event whose name the graftcontract registry does not
declare. The emit uses the real ``observe.emit`` idiom so the rule's
wrapper resolution (not just a name match) is what fires.
"""

from bsseqconsensusreads_tpu.utils import observe


def fx_finish(records):
    observe.emit("fx_phantom_event", {"records": records})  # seeded: contract-drift
