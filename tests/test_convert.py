"""AG->CT conversion transform vs scalar oracle."""

import numpy as np

from bsseqconsensusreads_tpu.alphabet import NBASE
from bsseqconsensusreads_tpu.ops.convert import convert_ag_to_ct
from bsseqconsensusreads_tpu.ops.encode import codes_to_seq, seq_to_codes
from bsseqconsensusreads_tpu.utils.oracle import oracle_convert_read
from bsseqconsensusreads_tpu.utils.testing import BASES, bisulfite_convert, random_genome


def run_window_convert(seq, quals, pos, genome, window_start, W, convert=True):
    """Place one read in a window and run the JAX op; decode results."""
    bases = np.full((1, W), NBASE, dtype=np.int8)
    q = np.zeros((1, W), dtype=np.float32)
    cover = np.zeros((1, W), dtype=bool)
    off = pos - window_start
    codes = seq_to_codes(seq)
    bases[0, off : off + len(codes)] = codes
    q[0, off : off + len(codes)] = quals
    cover[0, off : off + len(codes)] = True
    ref_str = genome[window_start : window_start + W + 1]
    ref_str += "N" * (W + 1 - len(ref_str))
    ref = seq_to_codes(ref_str)
    out_b, out_q, out_c, la, rd = convert_ag_to_ct(
        bases, q, cover, ref, np.array([convert])
    )
    out_b, out_q, out_c = np.asarray(out_b), np.asarray(out_q), np.asarray(out_c)
    if not out_c[0].any():
        return "", [], None, int(la[0]), int(rd[0])
    idx = np.nonzero(out_c[0])[0]
    assert (np.diff(idx) == 1).all(), "coverage must stay contiguous"
    new_pos = window_start + idx[0]
    return (
        codes_to_seq(out_b[0, idx]),
        [int(v) for v in out_q[0, idx]],
        new_pos,
        int(la[0]),
        int(rd[0]),
    )


class TestConvertVsOracle:
    def test_random_reads(self):
        rng = np.random.default_rng(7)
        name, genome = random_genome(rng, 3000)
        for trial in range(40):
            pos = int(rng.integers(1, 2800))
            length = int(rng.integers(10, 120))
            # B-strand-like read: bisulfite-converted bottom strand + noise
            raw = genome[pos : pos + length]
            read = bisulfite_convert(raw, genome, pos, "B")
            read = "".join(
                c if rng.random() > 0.05 else BASES[rng.integers(0, 4)] for c in read
            )
            quals = [int(x) for x in rng.integers(2, 41, size=length)]
            want = oracle_convert_read(read, quals, pos, genome)
            got = run_window_convert(read, quals, pos, genome, pos - 4, 160)
            assert got[0] == want[0], f"trial {trial}: seq mismatch"
            assert got[1] == want[1], f"trial {trial}: qual mismatch"
            assert got[2] == want[2], f"trial {trial}: pos mismatch"
            assert got[3:] == want[3:], f"trial {trial}: la/rd mismatch"

    def test_read_at_position_zero_not_prepended(self):
        rng = np.random.default_rng(8)
        _, genome = random_genome(rng, 200)
        read = genome[0:30].replace("G", "A")  # force conversions
        quals = [30] * 30
        got = run_window_convert(read, quals, 0, genome, 0, 128)
        want = oracle_convert_read(read, quals, 0, genome)
        assert got[0] == want[0]
        assert got[3] == 0  # LA=0: no room to prepend

    def test_passthrough_read_untouched(self):
        rng = np.random.default_rng(9)
        _, genome = random_genome(rng, 500)
        read = genome[100:150]
        quals = [33] * 50
        got = run_window_convert(read, quals, 100, genome, 96, 128, convert=False)
        assert got[0] == read
        assert got[1] == quals
        assert got[2] == 100
        assert got[3:] == (0, 0)


class TestConvertSemantics:
    def test_a_over_g_restored(self):
        # genome ...G..., read A at that position -> G
        genome = "TTTTGTTTT"
        got = run_window_convert("TATT", [30] * 4, 3, genome, 2, 128)
        # prepended base = genome[2]='T'; read T A T T -> T G T T
        assert got[0] == "TTGTT"
        assert got[2] == 2

    def test_c_not_cpg_converted_to_t(self):
        genome = "AAACAAAA"  # C at 3, next base A -> not CpG
        got = run_window_convert("CAA", [30] * 3, 3, genome, 1, 128)
        assert got[0][1] == "T"  # the C -> T

    def test_methylated_cpg_pair_rewrite(self):
        # ref CG at positions 3-4; read C,A -> T,G (signal transfer)
        genome = "TTTCGTTTT"
        got = run_window_convert("CAT", [30] * 3, 3, genome, 2, 128)
        assert got[0] == "TTGT"  # prepend T, then C->T, A->G, T stays

    def test_cpg_without_next_a_keeps_c(self):
        genome = "TTTCGTTTT"
        got = run_window_convert("CTT", [30] * 3, 3, genome, 2, 128)
        assert got[0][1] == "C"  # C kept: next read base is T, not A

    def test_trailing_c_before_g_trimmed(self):
        # read ends in C at a ref C, next ref base G -> trim + RD=1
        genome = "TTTTTCGTT"
        read = "TTC"  # maps at 3: positions 3,4,5; genome[6]='G'
        got = run_window_convert(read, [30] * 3, 3, genome, 2, 128)
        assert got[4] == 1  # RD set
        assert not got[0].endswith("C")
        assert len(got[1]) == len(got[0])

    def test_prepended_base_is_itself_converted(self):
        # prepend column lands on a ref C in CpG with next read base A:
        # the synthetic base must go through the same rules (ref-sub then T)
        genome = "TTCGTTTTT"
        # read maps at 3 (the G), first base A
        got = run_window_convert("ATT", [30] * 3, 3, genome, 1, 128)
        # prepend = genome[2] = 'C'; CpG (C at 2, G at 3), next read base A
        # -> prepended C becomes T, the A becomes G
        assert got[0] == "TGTT"
