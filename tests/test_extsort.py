"""External-merge sort + streaming zipper: correctness vs the in-memory
versions, and peak-RSS bounds on >=100k-family inputs (the round-1 VERDICT
item: kill the reference's whole-file-in-RAM sort/merge boundaries,
tools/2.extend_gap.py:155-178, main.snake.py:106,152, README.md:83)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from bsseqconsensusreads_tpu.io.bam import BamHeader, BamRecord, CMATCH
from bsseqconsensusreads_tpu.pipeline.extsort import external_sort, sorted_write
from bsseqconsensusreads_tpu.pipeline.record_ops import (
    coordinate_key,
    coordinate_sort,
    name_key,
    name_sort,
    template_coordinate_key,
    template_coordinate_sort,
    zipper_bams,
    zipper_bams_stream,
)

HEADER = BamHeader("@HD\tVN:1.6\n", [("chr1", 100000), ("chr2", 100000)])


def _random_records(rng, n, with_mi=True):
    recs = []
    for i in range(n):
        flag = int(rng.choice([99, 147, 163, 83, 4]))
        mapped = flag != 4
        r = BamRecord(
            qname=f"q{int(rng.integers(0, n))}",
            flag=flag,
            ref_id=int(rng.integers(0, 2)) if mapped else -1,
            pos=int(rng.integers(0, 90000)) if mapped else -1,
            mapq=60,
            cigar=[(CMATCH, 8)] if mapped else [],
            next_ref_id=0 if mapped else -1,
            next_pos=int(rng.integers(0, 90000)) if mapped else -1,
            seq="ACGTACGT",
            qual=bytes([30] * 8),
        )
        if with_mi:
            r.set_tag("MI", f"{int(rng.integers(0, 50))}/{'A' if i % 2 else 'B'}", "Z")
        recs.append(r)
    return recs


def _ids(recs):
    return [(r.qname, r.flag, r.ref_id, r.pos) for r in recs]


@pytest.mark.parametrize("key,ref", [
    (coordinate_key, coordinate_sort),
    (name_key, name_sort),
    (template_coordinate_key, template_coordinate_sort),
])
@pytest.mark.parametrize("buffer_records", [7, 100, 10000])
def test_external_sort_matches_in_memory(key, ref, buffer_records, tmp_path):
    rng = np.random.default_rng(11)
    recs = _random_records(rng, 300)
    got = list(external_sort(
        iter(recs), key, HEADER, workdir=str(tmp_path),
        buffer_records=buffer_records,
    ))
    assert _ids(got) == _ids(ref(recs))
    # all spill shards cleaned up
    assert os.listdir(tmp_path) == []


def test_external_sort_stability_key_payload(tmp_path):
    """Records with equal keys keep full payloads (tags survive the BGZF
    round-trip through spill shards)."""
    rng = np.random.default_rng(12)
    recs = _random_records(rng, 50)
    got = list(external_sort(
        iter(recs), coordinate_key, HEADER, workdir=str(tmp_path),
        buffer_records=9,
    ))
    assert sorted(str(r.get_tag("MI")) for r in got) == sorted(
        str(r.get_tag("MI")) for r in recs
    )


def test_sorted_write(tmp_path):
    rng = np.random.default_rng(13)
    recs = _random_records(rng, 120)
    out = str(tmp_path / "out.bam")
    n = sorted_write(iter(recs), coordinate_key, out, HEADER,
                     workdir=str(tmp_path), buffer_records=11)
    assert n == 120
    from bsseqconsensusreads_tpu.io.bam import BamReader

    with BamReader(out) as r:
        assert _ids(list(r)) == _ids(coordinate_sort(recs))


def test_zipper_stream_matches_in_memory(tmp_path):
    rng = np.random.default_rng(14)
    aligned = _random_records(rng, 200, with_mi=False)
    # unaligned partners for half the names, carrying consensus tags
    unaligned = []
    seen = set()
    for r in aligned[::2]:
        k = (r.qname, bool(r.flag & 0x80))
        if k in seen:
            continue
        seen.add(k)
        u = BamRecord(qname=r.qname, flag=77 if not k[1] else 141,
                      ref_id=-1, pos=-1, seq="ACGTACGT", qual=bytes([30] * 8))
        u.set_tag("MI", "9/A", "Z")
        u.set_tag("cD", 3, "i")
        unaligned.append(u)
    import copy

    want = zipper_bams(copy.deepcopy(aligned), unaligned)
    got = list(zipper_bams_stream(
        copy.deepcopy(aligned), iter(unaligned), HEADER,
        workdir=str(tmp_path), buffer_records=13,
    ))
    assert _ids(got) == _ids(want)
    assert [r.tags.get("MI") for r in got] == [r.tags.get("MI") for r in want]
    assert [r.tags.get("cD") for r in got] == [r.tags.get("cD") for r in want]


# ---- peak-RSS bounds (subprocess so the cap covers the whole run) ---------

#: 100k families = 400k records (~0.6 GB if ever materialized as Python
#: objects, before sort copies). Caps are ~2x the measured streaming peak
#: and well under the materialized footprint; the reference needs 100 GB
#: for this shape of work (README.md:83).
N_FAMILIES = 100_000
SELF_CAP_MB = 1100
ZIPPER_CAP_MB = 700
GROUP_CAP_MB = 700


def _run_helper(mode: str, tmp_path) -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=1")
    r = subprocess.run(
        [sys.executable, "-m", "tests.memhelper", mode, str(tmp_path),
         str(N_FAMILIES)],
        capture_output=True, text=True, timeout=1800, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert r.returncode == 0, r.stderr[-3000:]
    return json.loads(r.stdout.strip().splitlines()[-1])


@pytest.mark.slow
def test_peak_rss_self_pipeline_bounded(tmp_path):
    out = _run_helper("self", tmp_path)
    assert out["families"] == N_FAMILIES
    assert out["rss_mb"] < SELF_CAP_MB, out


@pytest.mark.slow
def test_peak_rss_zipper_bounded(tmp_path):
    out = _run_helper("zipper", tmp_path)
    assert out["records"] == 4 * N_FAMILIES
    assert out["rss_mb"] < ZIPPER_CAP_MB, out


def test_multipass_merge_bounded_fanin(tmp_path, monkeypatch):
    """>MERGE_FANIN runs trigger the multi-pass pre-merge; output identical."""
    from bsseqconsensusreads_tpu.pipeline import extsort

    monkeypatch.setattr(extsort, "MERGE_FANIN", 3)
    rng = np.random.default_rng(15)
    recs = _random_records(rng, 400)
    got = list(extsort.external_sort(
        iter(recs), coordinate_key, HEADER, workdir=str(tmp_path),
        buffer_records=10,  # 40 runs -> 3 merge passes at fanin 3
    ))
    assert _ids(got) == _ids(coordinate_sort(recs))
    assert os.listdir(tmp_path) == []


def test_deep_threshold_above_encode_cap_not_skipped():
    """Families between encode's MAX_TEMPLATES default and a larger
    deep_threshold must be processed on the normal path, not skipped."""
    from bsseqconsensusreads_tpu.io.bam import BamRecord, CMATCH
    from bsseqconsensusreads_tpu.ops import encode as encode_mod
    from bsseqconsensusreads_tpu.pipeline.calling import (
        StageStats,
        call_molecular_batches,
    )

    monkey_max = encode_mod.MAX_TEMPLATES  # sanity: default cap exists
    assert monkey_max == 4096
    depth = 24
    recs = []
    for d in range(depth):
        r = BamRecord(
            qname=f"t{d}", flag=99, ref_id=0, pos=10, mapq=60,
            cigar=[(CMATCH, 20)], seq="ACGTACGTACGTACGTACGT",
            qual=bytes([30] * 20),
        )
        r.set_tag("MI", "0/A", "Z")
        recs.append(r)
    stats = StageStats()
    # deep_threshold larger than the family: family stays on normal path
    out = [
        rec
        for b in call_molecular_batches(
            iter(recs), mode="self", grouping="adjacent", stats=stats,
            mesh=None, deep_threshold=100,
        )
        for rec in b
    ]
    assert stats.skipped_families == 0 and stats.families == 1
    assert len(out) == 1 and out[0].get_tag("cD") == depth


class TestRawExternalSort:
    """external_sort_raw must order encoded blobs exactly as external_sort
    orders the same records under coordinate_key (both stable)."""

    def _records(self, n, seed):
        import numpy as np

        from bsseqconsensusreads_tpu.io.bam import BamRecord, CMATCH

        rng = np.random.default_rng(seed)
        recs = []
        for i in range(n):
            length = int(rng.integers(5, 30))
            unmapped = rng.random() < 0.1
            recs.append(
                BamRecord(
                    qname=f"q{int(rng.integers(0, 40))}",
                    flag=int(rng.choice([99, 147, 83, 163, 4])),
                    ref_id=-1 if unmapped else int(rng.integers(0, 3)),
                    pos=-1 if unmapped else int(rng.integers(0, 1000)),
                    mapq=60,
                    cigar=[] if unmapped else [(CMATCH, length)],
                    next_ref_id=-1,
                    next_pos=-1,
                    tlen=0,
                    seq="".join(
                        "ACGT"[b] for b in rng.integers(0, 4, size=length)
                    ),
                    qual=bytes(rng.integers(2, 40, size=length).astype("u1")),
                )
            )
        return recs

    def test_matches_object_sort(self, tmp_path):
        from bsseqconsensusreads_tpu.io.bam import BamHeader, encode_record
        from bsseqconsensusreads_tpu.pipeline.extsort import (
            external_sort,
            external_sort_raw,
            iter_record_blobs,
        )
        from bsseqconsensusreads_tpu.pipeline.record_ops import coordinate_key

        header = BamHeader("@HD\tVN:1.6\n", [("c0", 5000), ("c1", 5000), ("c2", 5000)])
        recs = self._records(700, seed=4)
        want = [
            encode_record(r)
            for r in external_sort(
                iter(recs), coordinate_key, header,
                workdir=str(tmp_path), buffer_records=100,
            )
        ]
        got = list(
            external_sort_raw(
                iter_record_blobs(iter(recs)), header,
                workdir=str(tmp_path), buffer_records=100,
            )
        )
        assert got == want

    def test_single_buffer_no_spill(self, tmp_path):
        from bsseqconsensusreads_tpu.io.bam import BamHeader, encode_record
        from bsseqconsensusreads_tpu.pipeline.extsort import (
            external_sort,
            external_sort_raw,
            iter_record_blobs,
        )
        from bsseqconsensusreads_tpu.pipeline.record_ops import coordinate_key

        header = BamHeader("@HD\tVN:1.6\n", [("c0", 5000), ("c1", 5000), ("c2", 5000)])
        recs = self._records(40, seed=5)
        want = [
            encode_record(r)
            for r in external_sort(iter(recs), coordinate_key, header)
        ]
        assert list(external_sort_raw(iter_record_blobs(iter(recs)), header)) == want


class TestWriteBatchStream:
    """write_batch_stream: the shared stage/CLI batch writer."""

    def test_mixed_items_and_self_sort(self, tmp_path):
        from bsseqconsensusreads_tpu.io.bam import (
            BamHeader,
            BamReader,
            RawRecords,
            encode_record,
        )
        from bsseqconsensusreads_tpu.pipeline.extsort import write_batch_stream

        header = BamHeader("@HD\tVN:1.6\n", [("c0", 5000)])
        rng = np.random.default_rng(77)
        recs = TestRawExternalSort()._records(30, seed=7)
        blob = RawRecords(
            b"".join(encode_record(r) for r in recs[:10]), 10
        )
        batches = [[blob], recs[10:20], [], recs[20:]]

        # order-preserving mode: straight-through, counts intact
        out1 = str(tmp_path / "stream.bam")
        write_batch_stream(iter(batches), out1, header, mode="unaligned")
        with BamReader(out1) as r:
            got = [x.qname for x in r]
        assert got == [r_.qname for r_ in recs]

        # self mode: coordinate-sorted over the mixed items
        from bsseqconsensusreads_tpu.pipeline.record_ops import coordinate_key

        out2 = str(tmp_path / "sorted.bam")
        write_batch_stream(iter(batches), out2, header, mode="self")
        with BamReader(out2) as r:
            got_keys = [coordinate_key(x) for x in r]
        assert got_keys == sorted(got_keys)
        assert len(got_keys) == len(recs)


@pytest.mark.slow
def test_peak_rss_group_umi_bounded(tmp_path):
    """The UMI-grouping stage (two nested external sorts over 4*N_FAMILIES
    raw records) must stay O(buffer + position bucket): fgbio's
    GroupReadsByUmi holds its grouping state in a JVM heap."""
    out = _run_helper("group", tmp_path)
    assert out["records"] == 4 * N_FAMILIES
    assert out["molecules"] == N_FAMILIES
    assert out["rss_mb"] < GROUP_CAP_MB, out
