"""Wire-format + device-resident-genome parity tests.

The packed tunnel path (ops.wire + ops.refstore + duplex_call_wire) must be
bit-identical to the unpacked duplex_call_pipeline path — it is a transport
optimization, not a model change.
"""

import numpy as np
import pytest

import jax

from bsseqconsensusreads_tpu.alphabet import BASE_CODE, NBASE
from bsseqconsensusreads_tpu.models.duplex import (
    duplex_call_pipeline,
    duplex_call_wire,
    unpack_duplex_wire_outputs,
)
from bsseqconsensusreads_tpu.models.params import ConsensusParams
from bsseqconsensusreads_tpu.ops.refstore import RefStore, gather_windows
from bsseqconsensusreads_tpu.ops.wire import (
    pack_duplex_inputs,
    pack_lard,
    unpack_duplex_inputs,
    unpack_lard,
)

PARAMS = ConsensusParams(min_reads=0)


def random_batch(f=6, w=32, seed=0):
    rng = np.random.default_rng(seed)
    bases = rng.integers(0, 5, size=(f, 4, w)).astype(np.int8)
    cover = np.zeros((f, 4, w), dtype=bool)
    for fi in range(f):
        for r in range(4):
            a, b = sorted(rng.integers(1, w, size=2))
            cover[fi, r, a : b + 1] = True
    bases[~cover] = NBASE
    quals = np.where(cover, rng.integers(2, 41, size=(f, 4, w)), 0).astype(np.uint8)
    convert_mask = rng.integers(0, 2, size=(f, 4)).astype(bool)
    eligible = rng.integers(0, 2, size=f).astype(bool)
    return bases, quals, cover, convert_mask, eligible


def test_input_roundtrip():
    f, w = 5, 18
    bases, quals, cover, cmask, elig = random_batch(f, w, seed=1)
    starts = np.arange(f, dtype=np.int32)
    limits = np.full(f, 1000, dtype=np.int32)
    wire = pack_duplex_inputs(bases, quals, cover, cmask, elig, starts, limits)
    b, q, c, m, e = unpack_duplex_inputs(
        wire.nib, wire.qual, wire.meta, f, w, qual_mode=wire.qual_mode
    )
    # all codes (0..4 incl. NBASE=4) fit the 3-bit field exactly
    np.testing.assert_array_equal(np.asarray(b), bases)
    np.testing.assert_array_equal(np.asarray(q), quals)
    np.testing.assert_array_equal(np.asarray(c), cover)
    np.testing.assert_array_equal(np.asarray(m), cmask)
    np.testing.assert_array_equal(np.asarray(e), elig)


def test_lard_roundtrip():
    rng = np.random.default_rng(2)
    f = 7
    la = rng.integers(0, 2, size=(f, 4)).astype(np.int8)
    rd = rng.integers(0, 2, size=(f, 4)).astype(np.int8)
    words = np.asarray(pack_lard(la, rd))
    la2, rd2 = unpack_lard(words, f)
    np.testing.assert_array_equal(la2, la)
    np.testing.assert_array_equal(rd2, rd)


def test_refstore_window_gather_matches_host_fetch():
    rng = np.random.default_rng(3)
    seqs = {
        "chr1": "".join(rng.choice(list("ACGT"), size=300)),
        "chr2": "".join(rng.choice(list("ACGT"), size=120)),
    }
    store = RefStore(list(seqs), seqs=list(seqs.values()))
    width = 40
    cases = [(0, 10), (0, 280), (1, 0), (1, 100), (0, -5), (7, 10)]
    starts, limits = store.window_offsets(
        [c[0] for c in cases], [c[1] for c in cases]
    )
    got = np.asarray(
        gather_windows(jax.device_put(store.codes), starts, limits, width)
    )
    names = list(seqs)
    for i, (rid, ws) in enumerate(cases):
        want = np.full(width, NBASE, dtype=np.int8)
        if 0 <= rid < len(names) and ws >= 0:
            s = seqs[names[rid]][ws : ws + width]
            want[: len(s)] = BASE_CODE[
                np.frombuffer(s.encode(), dtype=np.uint8)
            ]
        np.testing.assert_array_equal(got[i], want, err_msg=f"case {i}: {rid},{ws}")


def test_wire_path_matches_unpacked_pipeline():
    f, w = 8, 32
    bases, quals, cover, cmask, elig = random_batch(f, w, seed=4)
    rng = np.random.default_rng(5)
    genome_codes = rng.integers(0, 4, size=2000).astype(np.int8)
    store = RefStore(["g"], codes=genome_codes, lengths=[2000])
    window_starts = rng.integers(0, 1900, size=f)
    starts, limits = store.window_offsets(np.zeros(f, dtype=int), window_starts)

    ref = np.asarray(gather_windows(store.device_codes, starts, limits, w + 1))
    want = jax.device_get(
        duplex_call_pipeline(
            bases, quals.astype(np.float32), cover, ref, cmask, elig, params=PARAMS
        )
    )

    wire = pack_duplex_inputs(bases, quals, cover, cmask, elig, starts, limits)
    out_wire = duplex_call_wire(
        wire.nib, wire.qual, wire.meta, wire.starts, wire.limits,
        store.device_codes, f, w, PARAMS, wire.qual_mode,
    )
    got = unpack_duplex_wire_outputs(jax.device_get(out_wire), f=f, w=w)

    np.testing.assert_array_equal(got["base"], np.asarray(want["base"]))
    np.testing.assert_array_equal(got["depth"], np.asarray(want["depth"]))
    np.testing.assert_array_equal(got["errors"], np.asarray(want["errors"]))
    np.testing.assert_array_equal(got["a_depth"], np.asarray(want["a_depth"]))
    np.testing.assert_array_equal(got["a_err"], np.asarray(want["a_err"]))
    np.testing.assert_array_equal(got["b_err"], np.asarray(want["b_err"]))
    np.testing.assert_array_equal(got["la"], np.asarray(want["la"]))
    np.testing.assert_array_equal(got["rd"], np.asarray(want["rd"]))

    # the b0-only wire ships no qual plane; the host reconstruction from
    # (shipped strand bits x this host's own evolved input quals) must be
    # bit-identical to the device-computed quals of the unpacked path
    assert "qual" not in got
    from bsseqconsensusreads_tpu.ops.reconstruct import (
        evolve_duplex_quals,
        reconstruct_duplex_quals,
    )

    evolved, cov = evolve_duplex_quals(cover, quals, got["la"], got["rd"], elig)
    # device presence (which also excludes in-span N observations) is a
    # subset of the host's evolved coverage — the qual lookups only ever
    # read evolved cells the device says were observed
    for role, (a_row, b_row) in enumerate(((0, 1), (3, 2))):
        assert not ((got["a_depth"][:, role] > 0) & ~cov[:, a_row]).any()
        assert not ((got["b_depth"][:, role] > 0) & ~cov[:, b_row]).any()
    got["qual"] = reconstruct_duplex_quals(got, evolved, PARAMS)
    np.testing.assert_array_equal(got["qual"], np.asarray(want["qual"]))


@pytest.mark.parametrize("n_levels,want_mode", [(3, "q2"), (9, "q4"), (30, "q8")])
def test_qual_codebook_roundtrip(n_levels, want_mode):
    rng = np.random.default_rng(31 + n_levels)
    f, w = 5, 24
    bases, _, cover, cmask, elig = random_batch(f, w, seed=8)
    levels = np.sort(rng.choice(np.arange(2, 60), size=n_levels, replace=False))
    quals = np.where(
        cover, levels[rng.integers(0, n_levels, size=(f, 4, w))], 0
    ).astype(np.uint8)
    starts = np.arange(f, dtype=np.uint32)
    limits = np.full(f, 900, dtype=np.uint32)
    wire = pack_duplex_inputs(
        bases, quals, cover, cmask, elig, starts, limits, qual_mode="auto"
    )
    assert wire.qual_mode == want_mode
    from bsseqconsensusreads_tpu.ops.wire import wire_section_sizes

    assert wire.to_words().size == sum(wire_section_sizes(f, w, qual_mode=want_mode))
    b, q, c, m, e = unpack_duplex_inputs(
        wire.nib, wire.qual, wire.meta, f, w, qual_mode=wire.qual_mode
    )
    # covered cells round-trip exactly; uncovered cells are never observed
    np.testing.assert_array_equal(np.asarray(q)[cover], quals[cover])
    np.testing.assert_array_equal(np.asarray(b), bases)
    np.testing.assert_array_equal(np.asarray(c), cover)


def test_out_of_range_quals_refuse_codebook_modes():
    """Phred > 93 (e.g. 0xff 'unavailable' bytes) must not silently alias the
    uncovered-cell sentinel: auto falls back to raw q8, explicit q2 raises."""
    f, w = 3, 16
    bases, _, cover, cmask, elig = random_batch(f, w, seed=12)
    quals = np.where(cover, 255, 0).astype(np.uint8)
    starts = np.arange(f, dtype=np.uint32)
    limits = np.full(f, 500, dtype=np.uint32)
    wire = pack_duplex_inputs(
        bases, quals, cover, cmask, elig, starts, limits, qual_mode="auto"
    )
    assert wire.qual_mode == "q8"
    _, q, *_ = unpack_duplex_inputs(
        wire.nib, wire.qual, wire.meta, f, w, qual_mode=wire.qual_mode
    )
    np.testing.assert_array_equal(np.asarray(q)[cover], quals[cover])
    with pytest.raises(ValueError, match="93"):
        pack_duplex_inputs(
            bases, quals, cover, cmask, elig, starts, limits, qual_mode="q2"
        )


def test_q2_wire_output_matches_q8_wire_output():
    """Quantized-qual transport must not change results: uncovered cells'
    qual placeholder (codebook[0] vs raw 0) must never leak into outputs."""
    from bsseqconsensusreads_tpu.models.duplex import duplex_call_wire_fused

    f, w = 8, 32
    bases, _, cover, cmask, elig = random_batch(f, w, seed=9)
    rta3 = np.array([2, 12, 23, 37], dtype=np.uint8)
    rng = np.random.default_rng(10)
    quals = np.where(cover, rta3[rng.integers(0, 4, size=(f, 4, w))], 0).astype(
        np.uint8
    )
    genome_codes = rng.integers(0, 4, size=1200).astype(np.int8)
    store = RefStore(["g"], codes=genome_codes, lengths=[1200])
    starts, limits = store.window_offsets(
        np.zeros(f, dtype=int), rng.integers(0, 1100, size=f)
    )
    outs = {}
    for mode in ("q2", "q8"):
        wire = pack_duplex_inputs(
            bases, quals, cover, cmask, elig, starts, limits, qual_mode=mode
        )
        assert wire.qual_mode == mode
        outs[mode] = np.asarray(
            duplex_call_wire_fused(
                wire.to_words(), store.device_codes, f, w, PARAMS, mode
            )
        )
    np.testing.assert_array_equal(outs["q2"], outs["q8"])


def test_fused_single_array_wire_matches_five_array_wire():
    from bsseqconsensusreads_tpu.models.duplex import duplex_call_wire_fused
    from bsseqconsensusreads_tpu.ops.wire import (
        split_duplex_wire,
        wire_section_sizes,
    )

    f, w = 7, 30
    bases, quals, cover, cmask, elig = random_batch(f, w, seed=6)
    rng = np.random.default_rng(7)
    genome_codes = rng.integers(0, 4, size=1500).astype(np.int8)
    store = RefStore(["g"], codes=genome_codes, lengths=[1500])
    starts, limits = store.window_offsets(
        np.zeros(f, dtype=int), rng.integers(0, 1400, size=f)
    )
    wire = pack_duplex_inputs(bases, quals, cover, cmask, elig, starts, limits)
    words = wire.to_words()
    assert words.dtype == np.uint32
    assert words.size == sum(wire_section_sizes(f, w, qual_mode=wire.qual_mode))

    # device-side split restores the five sections exactly
    nib, qual, meta, st, li = (
        np.asarray(x)
        for x in split_duplex_wire(words, f, w, qual_mode=wire.qual_mode)
    )
    np.testing.assert_array_equal(nib, wire.nib)
    np.testing.assert_array_equal(qual, wire.qual)
    np.testing.assert_array_equal(meta, wire.meta)
    np.testing.assert_array_equal(st, wire.starts)
    np.testing.assert_array_equal(li, wire.limits)

    # end-to-end: fused call == five-array call, bit for bit
    want = np.asarray(
        duplex_call_wire(
            wire.nib, wire.qual, wire.meta, wire.starts, wire.limits,
            store.device_codes, f, w, PARAMS, wire.qual_mode,
        )
    )
    got = np.asarray(
        duplex_call_wire_fused(
            words, store.device_codes, f, w, PARAMS, wire.qual_mode
        )
    )
    np.testing.assert_array_equal(got, want)


def test_native_retire_matches_numpy_reference(monkeypatch):
    """The one-pass C retire (io.wirepack.duplex_retire) must reproduce
    the numpy reference (b0 unpack + evolve + table reconstruction)
    field for field."""
    from bsseqconsensusreads_tpu.io import wirepack
    from bsseqconsensusreads_tpu.ops.reconstruct import retire_duplex_wire

    if not wirepack.available():
        pytest.skip("native wirepack not built")
    f, w = 8, 32
    bases, quals, cover, cmask, elig = random_batch(f, w, seed=23)
    rng = np.random.default_rng(24)
    genome_codes = rng.integers(0, 4, size=2000).astype(np.int8)
    store = RefStore(["g"], codes=genome_codes, lengths=[2000])
    starts, limits = store.window_offsets(
        np.zeros(f, dtype=int), rng.integers(0, 1900, size=f)
    )
    wire = pack_duplex_inputs(bases, quals, cover, cmask, elig, starts, limits)
    out_wire = np.asarray(jax.device_get(duplex_call_wire(
        wire.nib, wire.qual, wire.meta, wire.starts, wire.limits,
        store.device_codes, f, w, PARAMS, wire.qual_mode,
    )))
    native = retire_duplex_wire(out_wire, f, w, cover, quals, elig, PARAMS)
    monkeypatch.setattr(wirepack, "available", lambda: False)
    ref = retire_duplex_wire(out_wire, f, w, cover, quals, elig, PARAMS)
    assert set(native) == set(ref)
    for k in ref:
        np.testing.assert_array_equal(
            np.asarray(native[k]), np.asarray(ref[k]), err_msg=k
        )
