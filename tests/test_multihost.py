"""Multi-host (multi-process) family sharding: 2 simulated hosts x 4 CPU
devices vs the single-process reference, bit-for-bit on the packed wire.

The reference scales by files + processes (SURVEY.md §5.8); this validates
the framework's jax.distributed equivalent end to end: coordination-service
init, host-major global mesh, zero-copy global batch assembly from
process-local rows, sharded execution, and local-shard retrieval.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


class TestMultihostHelpers:
    """process_count == 1 degeneracy of the multihost helpers (in-process,
    8 virtual devices from conftest)."""

    def test_mesh_and_local_split(self):
        import jax

        from bsseqconsensusreads_tpu.parallel import multihost

        mesh = multihost.multihost_family_mesh()
        assert mesh.shape["data"] == len(jax.devices())
        n_local, first = multihost.local_family_count(16, mesh)
        assert (n_local, first) == (16, 0)  # single process owns everything
        with pytest.raises(ValueError, match="evenly"):
            multihost.local_family_count(15, mesh)

    def test_global_batch_roundtrip(self):
        from bsseqconsensusreads_tpu.parallel import multihost

        rng = np.random.default_rng(3)
        a = rng.integers(0, 100, size=(16, 3)).astype(np.int8)
        mesh = multihost.multihost_family_mesh()
        (ga,) = multihost.global_family_batch((a,), 16, mesh)
        assert ga.shape == (16, 3)
        np.testing.assert_array_equal(multihost.local_rows(ga, 16), a)


@pytest.mark.slow
def test_two_process_packed_molecular_matches_single(tmp_path):
    """Spawn 2 worker processes forming one jax.distributed job; their
    local output wire shards concatenated must equal the single-process
    packed molecular wire for the identical batch."""
    port = _free_port()
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(port), str(pid), str(tmp_path)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for pid in range(2)
    ]
    for p in procs:
        try:
            p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost worker timed out")

    skips = sorted(tmp_path.glob("skip_*.txt"))
    if skips:
        pytest.skip(f"distributed runtime unavailable: {skips[0].read_text()}")
    errors = sorted(tmp_path.glob("error_*.txt"))
    assert not errors, errors[0].read_text()[-1500:]

    parts = {}
    for pid in range(2):
        f = tmp_path / f"result_{pid}.npz"
        assert f.exists(), f"worker {pid} produced no result"
        parts[pid] = np.load(f)
    # host-major mesh: process 0 owns the first half of the family rows
    assert parts[0]["first"] < parts[1]["first"]
    got = np.concatenate([parts[0]["words"], parts[1]["words"]])

    from bsseqconsensusreads_tpu.models.molecular import (
        packed_molecular_kernel,
    )
    from bsseqconsensusreads_tpu.models.params import ConsensusParams

    F, T, W = 16, 5, 64
    rng = np.random.default_rng(77)  # the workers' exact batch
    bases = rng.integers(0, 4, size=(F, T, 2, W)).astype(np.int8)
    bases[rng.random(bases.shape) < 0.25] = 4
    quals = rng.integers(2, 41, size=bases.shape).astype(np.uint8)
    want = np.asarray(packed_molecular_kernel()(bases, quals, ConsensusParams()))
    np.testing.assert_array_equal(got, want)


def test_local_rows_count_mismatch_raises():
    from bsseqconsensusreads_tpu.parallel import multihost

    rng = np.random.default_rng(9)
    a = rng.integers(0, 50, size=(16, 2)).astype(np.int8)
    mesh = multihost.multihost_family_mesh()
    (ga,) = multihost.global_family_batch((a,), 16, mesh)
    with pytest.raises(ValueError, match="local rows"):
        multihost.local_rows(ga, 12)
