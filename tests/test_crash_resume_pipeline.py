"""Full-pipeline crash/resume under the round-3 machinery.

REAL subprocesses run the self-aligned pipeline with intra-stage
checkpoints over the current default engines (C-grouped columnar ingest,
depth-bucketed batching, native batch emit) and hard-crash (os._exit)
at scripted points; fresh processes resume from the durable shards. The
final BAM must be byte-identical to an uninterrupted run — the combined
determinism contract of skip_batches replay across the grouped stream,
bucketed chunk composition, and raw-blob sort finalize (SURVEY.md §5.4).

Crash coverage (ISSUE 3): mid-MOLECULAR (the original wrapper-based
kill), mid-DUPLEX (failpoint `exit` at a duplex batch), and
mid-FINALIZE with a corrupt partial shard present (failpoint `exit`
inside the duplex finalize + a flipped byte — resume must quarantine
and recompute, verified byte-identical).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from bsseqconsensusreads_tpu.io.bam import BamHeader, BamWriter
from bsseqconsensusreads_tpu.utils.testing import (
    random_genome,
    stream_duplex_families,
    write_fasta,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = """
import os, sys
os.environ["BSSEQ_TPU_BACKEND"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
from bsseqconsensusreads_tpu.pipeline import stages, calling

crash_after = int(os.environ.get("CRASH_AFTER", "0"))
if crash_after:
    orig = calling.call_molecular_batches
    def dying(*a, **k):
        for i, b in enumerate(orig(*a, **k)):
            if i >= crash_after:
                os._exit(9)  # hard crash: no cleanup, no atexit
            yield b
    stages.call_molecular_batches = dying

from bsseqconsensusreads_tpu.config import FrameworkConfig
from bsseqconsensusreads_tpu.pipeline.stages import run_pipeline

wd, bam, outdir = sys.argv[1:4]
cfg = FrameworkConfig(
    genome_dir=wd, genome_fasta_file_name="genome.fa", tmp=wd,
    aligner="self", grouping="coordinate", batch_families=8,
    checkpoint_every=2,
)
target, _, stats = run_pipeline(cfg, bam, outdir=outdir)
import json
print(json.dumps({
    "target": target,
    "batches": {k: s.as_dict().get("batches", 0) for k, s in stats.items()},
}))
print(target)
"""


@pytest.fixture(scope="module")
def crash_env(tmp_path_factory):
    """Shared input + worker + an uninterrupted reference run."""
    wd = tmp_path_factory.mktemp("crash_resume")
    rng = np.random.default_rng(88)
    codes = rng.integers(0, 4, size=40_000).astype(np.int8)
    from bsseqconsensusreads_tpu.ops.encode import codes_to_seq

    write_fasta(str(wd / "genome.fa"), "chr1", codes_to_seq(codes))
    header = BamHeader("@HD\tVN:1.6\tSO:coordinate\n", [("chr1", 40_000)])
    bam = str(wd / "input" / "in.bam")
    os.makedirs(os.path.dirname(bam))
    with BamWriter(bam, header) as w:
        for rec in stream_duplex_families(
            codes, 120, read_len=60, bisulfite=True,
            templates_for=lambda f: 1 if f % 3 else 2,
        ):
            w.write(rec)
    worker = wd / "worker.py"
    worker.write_text(WORKER)
    env = dict(os.environ, PYTHONPATH=REPO, BSSEQ_TPU_BACKEND="cpu")
    env.pop("BSSEQ_TPU_FAILPOINTS", None)

    def run(outdir, crash_after=0, failpoints=""):
        e = dict(env, CRASH_AFTER=str(crash_after))
        if failpoints:
            e["BSSEQ_TPU_FAILPOINTS"] = failpoints
        return subprocess.run(
            [sys.executable, str(worker), str(wd), bam, outdir],
            env=e, capture_output=True, text=True, timeout=600,
        )

    cp = run(str(wd / "out_plain"))
    assert cp.returncode == 0, cp.stderr[-2000:]
    payload = json.loads(cp.stdout.strip().splitlines()[0])
    return {
        "wd": wd,
        "run": run,
        "plain_bytes": open(payload["target"], "rb").read(),
        "plain_batches": payload["batches"],
    }


def _payload(cp) -> dict:
    return json.loads(cp.stdout.strip().splitlines()[0])


def _scraps(outdir) -> list[str]:
    return [f for f in os.listdir(outdir) if ".ckpt" in f or ".part" in f]


@pytest.mark.slow
def test_subprocess_crash_resume_byte_identical(crash_env):
    # crash after 3 chunks (checkpoint_every=2 -> 2 durable batches)
    out_crash = str(crash_env["wd"] / "out_crash")
    cp = crash_env["run"](out_crash, crash_after=3)
    assert cp.returncode == 9
    # durable evidence of the partial run
    assert _scraps(out_crash), os.listdir(out_crash)

    # resume in a fresh process
    cp = crash_env["run"](out_crash)
    assert cp.returncode == 0, cp.stderr[-2000:]
    resumed = _payload(cp)

    assert open(resumed["target"], "rb").read() == crash_env["plain_bytes"]
    # scratch cleaned up after finalize
    assert _scraps(out_crash) == []


@pytest.mark.slow
def test_subprocess_duplex_crash_resume_byte_identical(crash_env):
    """Crash/resume coverage for the DUPLEX caller (molecular-only before
    ISSUE 3): a failpoint hard-kills the run at a duplex batch; the
    resume skips the molecular stage entirely (its target is final) and
    re-executes only the duplex suffix."""
    out_crash = str(crash_env["wd"] / "out_crash_duplex")
    cp = crash_env["run"](
        out_crash,
        # batch 5: with the depth-1 retire pipeline and checkpoint_every=2
        # at least one duplex shard is durable before the kill
        failpoints="dispatch_kernel=exit:9@batch=5@stage=duplex",
    )
    assert cp.returncode == 9, cp.stderr[-2000:]
    scraps = _scraps(out_crash)
    assert any("_duplex_" in f for f in scraps), scraps

    cp = crash_env["run"](out_crash)
    assert cp.returncode == 0, cp.stderr[-2000:]
    resumed = _payload(cp)
    assert open(resumed["target"], "rb").read() == crash_env["plain_bytes"]
    # only the undone duplex suffix re-ran through the kernel
    assert 0 < resumed["batches"]["duplex"] < crash_env["plain_batches"]["duplex"]
    assert "molecular" not in resumed["batches"]  # rule skipped whole
    assert _scraps(out_crash) == []


@pytest.mark.slow
def test_subprocess_crash_in_finalize_with_corrupt_shard(crash_env):
    """Hard crash INSIDE the duplex finalize (hit=2: the molecular
    finalize is hit 1) leaves all duplex shards durable plus a partial
    .finalize.tmp; one shard is then corrupted on disk. The resume must
    quarantine it, recompute its batches, and still reproduce the
    reference bytes."""
    out_crash = str(crash_env["wd"] / "out_crash_finalize")
    cp = crash_env["run"](out_crash, failpoints="ckpt_finalize=exit:9@hit=2")
    assert cp.returncode == 9, cp.stderr[-2000:]
    shards = sorted(
        f for f in os.listdir(out_crash)
        if "_duplex_" in f and ".part" in f and f.endswith(".bam")
    )
    assert len(shards) >= 2, os.listdir(out_crash)
    victim = os.path.join(out_crash, shards[-2])
    blob = bytearray(open(victim, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(victim, "wb").write(bytes(blob))

    cp = crash_env["run"](out_crash)
    assert cp.returncode == 0, cp.stderr[-2000:]
    resumed = _payload(cp)
    assert open(resumed["target"], "rb").read() == crash_env["plain_bytes"]
    # the corrupt shard's batches (and the orphaned suffix) re-executed
    assert resumed["batches"]["duplex"] > 0
    assert _scraps(out_crash) == []


@pytest.mark.slow
def test_elastic_worker_crash_hands_checkpoints_to_respawn(crash_env):
    """graftswarm leg: an elastic worker hard-killed at a checkpoint
    shard write (ckpt_shard_write exit, same site as the single-process
    drills) is respawned; the requeued slice resumes from the dead
    worker's durable shard prefix in the slice-keyed work dir, and the
    merged output is byte-identical to the uninterrupted single-process
    run. The `slice_requeued` ledger line records the checkpoint
    fingerprint handoff (batches_kept > 0)."""
    wd = crash_env["wd"]
    cfgfile = wd / "elastic_cfg.yaml"
    cfgfile.write_text(
        "backend: cpu\naligner: self\ngrouping: coordinate\n"
        "batch_families: 8\ncheckpoint_every: 2\n"
    )
    outdir = str(wd / "out_elastic_crash")
    ledger = str(wd / "elastic_crash_ledger.jsonl")
    env = dict(
        os.environ,
        PYTHONPATH=REPO,
        BSSEQ_TPU_BACKEND="cpu",
        JAX_PLATFORMS="cpu",
        BSSEQ_TPU_STATS=ledger,
    )
    env.pop("BSSEQ_TPU_FAILPOINTS", None)
    cp = subprocess.run(
        [sys.executable, "-m", "bsseqconsensusreads_tpu.cli",
         "elastic", "run",
         "--config", str(cfgfile),
         "--bam", str(wd / "input" / "in.bam"),
         "--reference", str(wd / "genome.fa"),
         "--outdir", outdir,
         "--workers", "1", "--slices", "2",
         # hit=3: two checkpoint manifests (every=2) are durable first
         "--worker-failpoints", "w0:ckpt_shard_write=exit:9@hit=3"],
        env=env, capture_output=True, text=True, timeout=900,
    )
    assert cp.returncode == 0, cp.stdout + cp.stderr[-2000:]
    out = json.loads(cp.stdout)
    assert open(out["target"], "rb").read() == crash_env["plain_bytes"]
    report = out["report"]
    assert report["ok"], report["checks"]
    assert report["requeues"] >= 1 and report["workers_lost"] >= 1

    requeued = [
        json.loads(line)
        for line in open(ledger)
        if '"slice_requeued"' in line
    ]
    assert requeued and requeued[0]["worker"] == "w0"
    assert requeued[0]["batches_kept"] > 0
    spawns = sum(
        1 for line in open(ledger) if '"elastic_worker_spawn"' in line
    )
    assert spawns >= 2  # w0's first life + its respawn
