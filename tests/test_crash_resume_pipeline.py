"""Full-pipeline crash/resume under the round-3 machinery.

A REAL subprocess runs the self-aligned pipeline with intra-stage
checkpoints over the current default engines (C-grouped columnar ingest,
depth-bucketed batching, native batch emit) and hard-crashes (os._exit)
mid-molecular-stage; a fresh process resumes from the durable shards. The
final BAM must be byte-identical to an uninterrupted run — the combined
determinism contract of skip_batches replay across the grouped stream,
bucketed chunk composition, and raw-blob sort finalize (SURVEY.md §5.4).
"""

from __future__ import annotations

import os
import subprocess
import sys

import numpy as np
import pytest

from bsseqconsensusreads_tpu.io.bam import BamHeader, BamWriter
from bsseqconsensusreads_tpu.utils.testing import (
    random_genome,
    stream_duplex_families,
    write_fasta,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = """
import os, sys
os.environ["BSSEQ_TPU_BACKEND"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
from bsseqconsensusreads_tpu.pipeline import stages, calling

crash_after = int(os.environ.get("CRASH_AFTER", "0"))
if crash_after:
    orig = calling.call_molecular_batches
    def dying(*a, **k):
        for i, b in enumerate(orig(*a, **k)):
            if i >= crash_after:
                os._exit(9)  # hard crash: no cleanup, no atexit
            yield b
    stages.call_molecular_batches = dying

from bsseqconsensusreads_tpu.config import FrameworkConfig
from bsseqconsensusreads_tpu.pipeline.stages import run_pipeline

wd, bam, outdir = sys.argv[1:4]
cfg = FrameworkConfig(
    genome_dir=wd, genome_fasta_file_name="genome.fa", tmp=wd,
    aligner="self", grouping="coordinate", batch_families=8,
    checkpoint_every=2,
)
target, _, _ = run_pipeline(cfg, bam, outdir=outdir)
print(target)
"""


@pytest.mark.slow
def test_subprocess_crash_resume_byte_identical(tmp_path):
    rng = np.random.default_rng(88)
    codes = rng.integers(0, 4, size=40_000).astype(np.int8)
    from bsseqconsensusreads_tpu.ops.encode import codes_to_seq

    write_fasta(str(tmp_path / "genome.fa"), "chr1", codes_to_seq(codes))
    header = BamHeader("@HD\tVN:1.6\tSO:coordinate\n", [("chr1", 40_000)])
    bam = str(tmp_path / "input" / "in.bam")
    os.makedirs(os.path.dirname(bam))
    with BamWriter(bam, header) as w:
        for rec in stream_duplex_families(
            codes, 120, read_len=60, bisulfite=True,
            templates_for=lambda f: 1 if f % 3 else 2,
        ):
            w.write(rec)
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    env = dict(os.environ, PYTHONPATH=REPO, BSSEQ_TPU_BACKEND="cpu")

    def run(outdir, crash_after=0):
        e = dict(env, CRASH_AFTER=str(crash_after))
        return subprocess.run(
            [sys.executable, str(worker), str(tmp_path), bam, outdir],
            env=e, capture_output=True, text=True, timeout=600,
        )

    # uninterrupted reference
    cp = run(str(tmp_path / "out_plain"))
    assert cp.returncode == 0, cp.stderr[-2000:]
    plain_target = cp.stdout.strip().splitlines()[-1]

    # crash after 3 chunks (checkpoint_every=2 -> 2 durable batches)
    out_crash = str(tmp_path / "out_crash")
    cp = run(out_crash, crash_after=3)
    assert cp.returncode == 9
    # durable evidence of the partial run
    scraps = [f for f in os.listdir(out_crash) if ".ckpt" in f or ".part" in f]
    assert scraps, os.listdir(out_crash)

    # resume in a fresh process
    cp = run(out_crash)
    assert cp.returncode == 0, cp.stderr[-2000:]
    resumed_target = cp.stdout.strip().splitlines()[-1]

    assert open(resumed_target, "rb").read() == open(plain_target, "rb").read()
    # scratch cleaned up after finalize
    scraps = [f for f in os.listdir(out_crash) if ".ckpt" in f or ".part" in f]
    assert scraps == []
