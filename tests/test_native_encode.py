"""Native molecular-encode digest (io.native.encode_scan/encode_fill +
ops.encode._encode_molecular_native) vs the per-record Python encoder:
identical tensors, meta, skip lists, and stage output bytes.

The C scan replicates encode_molecular_families pass 1 (template pairing by
qname with last-record-wins (qname, role) slots, RX majority with
first-insertion tie-break, per-slot orientation votes, lo/hi window over
every kept record) — this suite fuzzes exactly those semantics: softclips,
hardclips, indels under both policies, missing quals, duplicate slots, RX
ties and absences, all-softclip reads (est vs placed template-count
divergence), window/template-cap skips.
"""

import numpy as np
import pytest

from bsseqconsensusreads_tpu.io.bam import (
    BamHeader,
    BamReader,
    BamRecord,
    BamWriter,
    CDEL,
    CHARD_CLIP,
    CINS,
    CMATCH,
    CSOFT_CLIP,
    write_items,
)
from bsseqconsensusreads_tpu.ops.encode import encode_molecular_families
from bsseqconsensusreads_tpu.pipeline import ingest
from bsseqconsensusreads_tpu.pipeline.calling import (
    StageStats,
    _kept_template_count,
    call_molecular_batches,
)

pytestmark = pytest.mark.skipif(
    not ingest.available(), reason="native decoder not built"
)


def _messy_records(rng, n_families=60, base_start=20):
    """Families exercising every scan branch; coordinate-sorted on return."""
    records = []
    for fam in range(n_families):
        start = base_start + fam * 70
        kind = fam % 10
        mi = f"{fam}/A"
        depth = int(rng.integers(1, 5))
        for d in range(depth):
            for flag, pos in ((99, start), (147, start + 25)):
                cig = [(CMATCH, 30)]
                roll = int(rng.integers(0, 8))
                if roll == 0:
                    cig = [(CSOFT_CLIP, 4), (CMATCH, 26)]
                elif roll == 1:
                    cig = [(CMATCH, 26), (CSOFT_CLIP, 4)]
                elif roll == 2:
                    cig = [(CMATCH, 12), (CINS, 2), (CMATCH, 16)]
                elif roll == 3:
                    cig = [(CMATCH, 14), (CDEL, 3), (CMATCH, 13)]
                elif roll == 4:
                    cig = [(CHARD_CLIP, 3), (CMATCH, 30)]
                elif roll == 5 and d > 0:
                    cig = [(CSOFT_CLIP, 30)]  # trims to nothing: est-only
                read_len = sum(
                    n for op, n in cig if op in (CMATCH, CINS, CSOFT_CLIP)
                )
                seq = "".join(
                    "ACGT"[b] for b in rng.integers(0, 4, size=read_len)
                )
                qual = bytes(rng.integers(2, 41, size=read_len).tolist())
                if kind == 1 and d == 0:
                    qual = None  # missing quals (BAM '*' / 0xFF fill)
                rec = BamRecord(
                    qname=f"f{fam}d{d}", flag=flag, ref_id=0, pos=pos,
                    mapq=60, cigar=cig, next_ref_id=0,
                    next_pos=start + 25 if flag == 99 else start,
                    seq=seq, qual=qual,
                )
                rec.set_tag("MI", mi, "Z")
                if kind == 2:
                    pass  # no RX anywhere in the family
                elif kind == 3:
                    # two RX values, counts tied when depth is even: the
                    # majority must tie-break to the first-seen value
                    rec.set_tag("RX", "AA-CC" if d % 2 == 0 else "GG-TT", "Z")
                elif kind == 4 and d == 0:
                    pass  # one untagged read among tagged ones
                else:
                    rec.set_tag("RX", "AC-GT", "Z")
                records.append(rec)
        if kind == 5:
            # duplicate (qname, role) slot: a second flag-99 record for an
            # existing qname — last record must win the slot
            seq = "".join("ACGT"[b] for b in rng.integers(0, 4, size=30))
            rec = BamRecord(
                qname=f"f{fam}d0", flag=99, ref_id=0, pos=start + 2,
                mapq=60, cigar=[(CMATCH, 30)], next_ref_id=0,
                next_pos=start + 25, seq=seq,
                qual=bytes(rng.integers(2, 41, size=30).tolist()),
            )
            rec.set_tag("MI", mi, "Z")
            rec.set_tag("RX", "AC-GT", "Z")
            records.append(rec)
        if kind == 6:
            # hardclip-only family: every read drops -> skipped (empty)
            for rec in records[:]:
                pass
            only = BamRecord(
                qname=f"f{fam}hc", flag=0, ref_id=0, pos=start + 40000,
                mapq=60, cigar=[(CHARD_CLIP, 2), (CMATCH, 20)],
                next_ref_id=-1, next_pos=-1,
                seq="A" * 20, qual=bytes([30] * 20),
            )
            only.set_tag("MI", f"{fam}hc/A", "Z")
            records.append(only)
        if kind == 7:
            # window overflow: mate 600 bases away busts max_window=512
            seq = "".join("ACGT"[b] for b in rng.integers(0, 4, size=30))
            far = BamRecord(
                qname=f"f{fam}d0", flag=147, ref_id=0, pos=start + 600,
                mapq=60, cigar=[(CMATCH, 30)], next_ref_id=0, next_pos=start,
                seq=seq, qual=bytes(rng.integers(2, 41, size=30).tolist()),
            )
            far.set_tag("MI", mi, "Z")
            far.set_tag("RX", "AC-GT", "Z")
            records.append(far)
    records.sort(key=lambda r: (r.ref_id, r.pos))
    return records


@pytest.fixture(scope="module", params=[0, 1, 2])
def messy_bam(request, tmp_path_factory):
    tmp = tmp_path_factory.mktemp(f"natenc{request.param}")
    rng = np.random.default_rng(1000 + request.param)
    records = _messy_records(rng)
    header = BamHeader("@HD\tVN:1.6\tSO:coordinate\n", [("chr1", 200000)])
    path = str(tmp / "in.bam")
    with BamWriter(path, header) as w:
        w.write_all(records)
    return {"path": path, "header": header}


def _families(path, scan_policy):
    return list(
        ingest.GroupedColumnarStream(
            path, scan_policy=scan_policy
        ).iter_groups()
    )


def _assert_batches_equal(a, b):
    batch_a, skip_a = a
    batch_b, skip_b = b
    assert skip_a == skip_b
    assert batch_a.bases.shape == batch_b.bases.shape
    assert np.array_equal(batch_a.bases, batch_b.bases)
    assert np.array_equal(batch_a.quals, batch_b.quals)
    assert batch_a.indel_aligned == batch_b.indel_aligned
    assert batch_a.indel_dropped == batch_b.indel_dropped
    assert len(batch_a.meta) == len(batch_b.meta)
    for ma, mb in zip(batch_a.meta, batch_b.meta):
        assert (ma.mi, ma.ref_id, ma.window_start, ma.n_templates,
                ma.rx, tuple(ma.role_reverse)) == (
            mb.mi, mb.ref_id, mb.window_start, mb.n_templates,
            mb.rx, tuple(mb.role_reverse)
        )


class TestNativeEncodeParity:
    @pytest.mark.parametrize("policy", ["drop", "align"])
    def test_encode_parity(self, messy_bam, policy, monkeypatch):
        from bsseqconsensusreads_tpu.io import native

        fills = []
        real_fill = native.encode_fill
        monkeypatch.setattr(
            native, "encode_fill",
            lambda *a, **k: fills.append(1) or real_fill(*a, **k),
        )
        fams_scan = _families(messy_bam["path"], policy)
        fams_py = _families(messy_bam["path"], None)
        assert [f.mi for f in fams_scan] == [mi for mi, _ in fams_py]
        got = encode_molecular_families(
            fams_scan, max_window=512, indel_policy=policy
        )
        want = encode_molecular_families(
            fams_py, max_window=512, indel_policy=policy
        )
        assert fills, "native fill path was not exercised"
        _assert_batches_equal(got, want)

    def test_template_cap_skip_parity(self, messy_bam):
        got = encode_molecular_families(
            _families(messy_bam["path"], "drop"), max_window=512,
            max_templates=2,
        )
        want = encode_molecular_families(
            _families(messy_bam["path"], None), max_window=512,
            max_templates=2,
        )
        _assert_batches_equal(got, want)

    def test_ntpl_est_matches_kept_template_count(self, messy_bam):
        for policy in ("drop", "align"):
            fams_scan = _families(messy_bam["path"], policy)
            fams_py = _families(messy_bam["path"], None)
            for run, (mi, records) in zip(fams_scan, fams_py):
                assert run.mi == mi
                assert run.ntpl_est == _kept_template_count(records, policy), mi
                assert run.n == len(records)

    def test_scan_policy_mismatch_falls_back(self, messy_bam):
        """A stream scanned under one policy encoding under the other must
        take the per-record Python path (the digest would be wrong)."""
        fams = _families(messy_bam["path"], "drop")
        got = encode_molecular_families(
            fams, max_window=512, indel_policy="align"
        )
        want = encode_molecular_families(
            _families(messy_bam["path"], None), max_window=512,
            indel_policy="align",
        )
        _assert_batches_equal(got, want)


def test_stage_output_identical_with_scan(messy_bam, tmp_path):
    """Full molecular stage: scan-carrying stream vs tuple stream must be
    byte-identical (same chunks, same order, same consensus records)."""
    outs = {}
    for policy in ("drop", None):
        stats = StageStats()
        stream = ingest.GroupedColumnarStream(
            messy_bam["path"], scan_policy=policy
        )
        batches = call_molecular_batches(
            stream, mode="self", grouping="coordinate", stats=stats,
            mesh=None,
        )
        out = str(tmp_path / f"out_{policy}.bam")
        with BamWriter(out, messy_bam["header"], engine="python") as w:
            for b in batches:
                write_items(w, b)
        outs[policy] = open(out, "rb").read()
    assert outs["drop"] == outs[None] and len(outs["drop"]) > 100


def _duplex_records(rng, n_families=50, base_start=30):
    """Duplex-shaped families: 4-read groups plus every leftover class —
    unknown flags, duplicate rows, indels, hardclips, empty-after-trim."""
    records = []
    for fam in range(n_families):
        start = base_start + fam * 80
        kind = fam % 8
        mi = f"{fam}"
        for i, (flag, pos) in enumerate(
            ((99, start), (163, start), (83, start + 20), (147, start + 20))
        ):
            cig = [(CMATCH, 40)]
            roll = int(rng.integers(0, 6))
            if roll == 0:
                cig = [(CSOFT_CLIP, 5), (CMATCH, 35)]
            elif roll == 1:
                cig = [(CMATCH, 35), (CSOFT_CLIP, 5)]
            elif kind == 1 and roll == 2:
                cig = [(CMATCH, 18), (CINS, 2), (CMATCH, 20)]  # leftover
            elif kind == 2 and roll == 3:
                cig = [(CHARD_CLIP, 2), (CMATCH, 40)]  # dropped
            elif kind == 3 and roll == 4:
                cig = [(CSOFT_CLIP, 40)]  # empty after trim -> leftover
            read_len = sum(
                n for op, n in cig if op in (CMATCH, CINS, CSOFT_CLIP)
            )
            seq = "".join("ACGT"[b] for b in rng.integers(0, 4, size=read_len))
            qual = bytes(rng.integers(2, 41, size=read_len).tolist())
            if kind == 4 and i == 0:
                qual = None
            rec = BamRecord(
                qname=f"q{fam}:{i}", flag=flag, ref_id=0, pos=pos, mapq=60,
                cigar=cig, next_ref_id=0, next_pos=start, seq=seq, qual=qual,
            )
            rec.set_tag("MI", f"{mi}/{'AB'[i % 2]}", "Z")
            if not (kind == 5 and i < 2):  # first reads untagged: rx from
                rec.set_tag("RX", f"RX{fam % 3}", "Z")  # a later placed read
            records.append(rec)
        if kind == 6:  # duplicate row: second flag-99 record -> leftover
            rec = BamRecord(
                qname=f"q{fam}:dup", flag=99, ref_id=0, pos=start + 1,
                mapq=60, cigar=[(CMATCH, 40)], next_ref_id=0, next_pos=start,
                seq="A" * 40, qual=bytes([30] * 40),
            )
            rec.set_tag("MI", f"{mi}/A", "Z")
            records.append(rec)
        if kind == 7:  # unknown flag -> leftover
            rec = BamRecord(
                qname=f"q{fam}:odd", flag=0, ref_id=0, pos=start + 2,
                mapq=60, cigar=[(CMATCH, 40)], next_ref_id=0, next_pos=-1,
                seq="C" * 40, qual=bytes([30] * 40),
            )
            rec.set_tag("MI", f"{mi}/A", "Z")
            records.append(rec)
    records.sort(key=lambda r: (r.ref_id, r.pos))
    return records


class TestNativeDuplexEncodeParity:
    @pytest.fixture(scope="class", params=[0, 1])
    def duplex_bam(self, request, tmp_path_factory):
        tmp = tmp_path_factory.mktemp(f"natdup{request.param}")
        rng = np.random.default_rng(500 + request.param)
        records = _duplex_records(rng)
        header = BamHeader(
            "@HD\tVN:1.6\tSO:coordinate\n", [("chr1", 200000)]
        )
        path = str(tmp / "dup.bam")
        with BamWriter(path, header) as w:
            w.write_all(records)
        genome = "".join(
            "ACGT"[b] for b in np.random.default_rng(9).integers(
                0, 4, size=10000
            )
        )
        return {"path": path, "header": header, "genome": genome}

    def _encode(self, bam, scan_policy, **kw):
        from bsseqconsensusreads_tpu.ops.encode import encode_duplex_families

        fams = list(
            ingest.GroupedColumnarStream(
                bam["path"], strip_suffix=True, scan_policy=scan_policy
            ).iter_groups()
        )
        genome = bam["genome"]
        return encode_duplex_families(
            fams, lambda name, s, e: genome[s:e], ["chr1"], **kw
        )

    @pytest.mark.parametrize("max_window", [4096, 64])
    def test_duplex_encode_parity(self, duplex_bam, max_window):
        got_b, got_l, got_s = self._encode(
            duplex_bam, "duplex", max_window=max_window
        )
        want_b, want_l, want_s = self._encode(
            duplex_bam, None, max_window=max_window
        )
        assert got_s == want_s
        assert [(r.qname, r.flag, r.pos) for r in got_l] == [
            (r.qname, r.flag, r.pos) for r in want_l
        ]
        assert np.array_equal(got_b.bases, want_b.bases)
        assert np.array_equal(got_b.quals, want_b.quals)
        assert np.array_equal(got_b.cover, want_b.cover)
        assert np.array_equal(got_b.ref, want_b.ref)
        assert np.array_equal(got_b.convert_mask, want_b.convert_mask)
        assert np.array_equal(got_b.extend_eligible, want_b.extend_eligible)
        for ma, mb in zip(got_b.meta, want_b.meta):
            assert (ma.mi, ma.ref_id, ma.window_start, ma.n_templates,
                    ma.rx) == (
                mb.mi, mb.ref_id, mb.window_start, mb.n_templates, mb.rx
            )

    def test_duplex_stage_output_identical(self, duplex_bam, tmp_path):
        from bsseqconsensusreads_tpu.pipeline.calling import (
            call_duplex_batches,
        )

        genome = duplex_bam["genome"]
        outs = {}
        for policy in ("duplex", None):
            stream = ingest.GroupedColumnarStream(
                duplex_bam["path"], strip_suffix=True, scan_policy=policy
            )
            batches = call_duplex_batches(
                stream, lambda name, s, e: genome[s:e], ["chr1"],
                mode="self", grouping="coordinate", stats=StageStats(),
                mesh=None,
            )
            out = str(tmp_path / f"dup_{policy}.bam")
            with BamWriter(out, duplex_bam["header"], engine="python") as w:
                for b in batches:
                    write_items(w, b)
            outs[policy] = open(out, "rb").read()
        assert outs["duplex"] == outs[None] and len(outs["duplex"]) > 100


def test_deep_family_scan_parity(tmp_path):
    """A deep family (template count past the deep threshold) must route
    and encode identically with and without the scan digest."""
    rng = np.random.default_rng(77)
    records = []
    for t in range(40):
        seq = "".join("ACGT"[b] for b in rng.integers(0, 4, size=30))
        rec = BamRecord(
            qname=f"t{t}", flag=99, ref_id=0, pos=100 + (t % 3),
            mapq=60, cigar=[(CMATCH, 30)], next_ref_id=0, next_pos=100,
            seq=seq, qual=bytes(rng.integers(2, 41, size=30).tolist()),
        )
        rec.set_tag("MI", "0/A", "Z")
        rec.set_tag("RX", "AC-GT", "Z")
        records.append(rec)
    records.sort(key=lambda r: r.pos)
    header = BamHeader("@HD\tVN:1.6\tSO:coordinate\n", [("chr1", 10000)])
    path = str(tmp_path / "deep.bam")
    with BamWriter(path, header) as w:
        w.write_all(records)
    outs = {}
    for policy in ("drop", None):
        stream = ingest.GroupedColumnarStream(path, scan_policy=policy)
        batches = call_molecular_batches(
            stream, mode="self", grouping="coordinate",
            stats=StageStats(), mesh=None, deep_threshold=8,
        )
        out = str(tmp_path / f"deep_{policy}.bam")
        with BamWriter(out, header, engine="python") as w:
            for b in batches:
                write_items(w, b)
        outs[policy] = open(out, "rb").read()
    assert outs["drop"] == outs[None] and len(outs["drop"]) > 100
