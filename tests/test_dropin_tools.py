"""Drop-in tools exercised exactly as the Snakemake rule bodies would.

The north-star contract (BASELINE.json; reference main.snake.py:121-164) is
that `tools/call_molecular_consensus_tpu.py` / `call_duplex_consensus_tpu.py`
slot into the reference's rule shapes as `shell:` subprocesses. These tests
invoke them that way — fresh interpreter, documented arguments, reference-
style config.yaml for the `run` entry — and assert the output BAMs are
byte-identical to the in-process pipeline (round-2 VERDICT item 7).
"""

from __future__ import annotations

import os
import subprocess
import sys

import numpy as np
import pytest

from bsseqconsensusreads_tpu.io.bam import BamHeader, BamReader, BamWriter
from bsseqconsensusreads_tpu.utils.testing import (
    make_aligned_duplex_group,
    make_grouped_bam_records,
    random_genome,
    write_fasta,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_tool(script: str, argv: list[str]) -> subprocess.CompletedProcess:
    env = dict(
        os.environ,
        PYTHONPATH=REPO,
        BSSEQ_TPU_BACKEND="cpu",  # subprocesses must never grab the tunnel
    )
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", script), *argv],
        capture_output=True, text=True, timeout=600, env=env,
    )


@pytest.fixture(scope="module")
def molecular_input(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("dropin_mol")
    rng = np.random.default_rng(77)
    name, genome = random_genome(rng, 6000)
    header, records = make_grouped_bam_records(rng, name, genome, n_families=8)
    inp = str(tmp / "grouped.bam")
    with BamWriter(inp, header) as w:
        w.write_all(records)
    return tmp, inp


@pytest.fixture(scope="module")
def duplex_input(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("dropin_dup")
    rng = np.random.default_rng(78)
    name, genome = random_genome(rng, 4000)
    fasta = str(tmp / "genome.fa")
    write_fasta(fasta, name, genome)
    header = BamHeader("@HD\tVN:1.6\tSO:coordinate\n", [(name, len(genome))])
    records = []
    for gi in range(5):
        records += make_aligned_duplex_group(
            rng, name, genome, gi, 100 + 300 * gi, 60
        )
    inp = str(tmp / "aligned.bam")
    with BamWriter(inp, header) as w:
        w.write_all(records)
    return tmp, inp, fasta


def test_molecular_dropin_subprocess_matches_inprocess(molecular_input):
    """The rule-shape invocation (main.snake.py:46-55's replacement):
    `python3 tools/call_molecular_consensus_tpu.py -i IN -o OUT`."""
    tmp, inp = molecular_input
    sub_out = str(tmp / "sub.bam")
    cp = _run_tool("call_molecular_consensus_tpu.py",
                   ["-i", inp, "-o", sub_out])
    assert cp.returncode == 0, cp.stderr[-2000:]
    # stderr carries the stage stats JSON (observability contract)
    assert '"families"' in cp.stderr

    from bsseqconsensusreads_tpu.cli import main as cli_main

    in_out = str(tmp / "inproc.bam")
    assert cli_main(["molecular", "-i", inp, "-o", in_out]) == 0
    sub_bytes = open(sub_out, "rb").read()
    assert sub_bytes == open(in_out, "rb").read()
    n = sum(1 for _ in BamReader(sub_out))
    assert n > 0


def test_duplex_dropin_subprocess_matches_inprocess(duplex_input):
    """The four-rule-chain replacement (main.snake.py:121-164):
    `python3 tools/call_duplex_consensus_tpu.py -i IN -o OUT --reference REF`.
    """
    tmp, inp, fasta = duplex_input
    sub_out = str(tmp / "sub.bam")
    cp = _run_tool("call_duplex_consensus_tpu.py",
                   ["-i", inp, "-o", sub_out, "--reference", fasta])
    assert cp.returncode == 0, cp.stderr[-2000:]

    from bsseqconsensusreads_tpu.cli import main as cli_main

    in_out = str(tmp / "inproc.bam")
    assert cli_main(
        ["duplex", "-i", inp, "-o", in_out, "--reference", fasta]
    ) == 0
    assert open(sub_out, "rb").read() == open(in_out, "rb").read()
    recs = list(BamReader(sub_out))
    assert len(recs) == 10  # 5 groups x R1+R2
    for rec in recs:
        tags = dict(rec.tags)
        assert "MI" in tags and "RX" in tags


def test_duplex_dropin_passthrough_flag(duplex_input):
    """--passthrough writes off-vocabulary leftovers through with the
    reference's convert-stage treatment (flag 0 passes verbatim,
    tools/1.convert_AG_to_CT.py:70-72); the default drops them."""
    from bsseqconsensusreads_tpu.io.bam import BamRecord, CMATCH

    tmp, inp, fasta = duplex_input
    # input + one unpaired flag-0 record (off the 99/163/83/147 vocabulary)
    with BamReader(inp) as r:
        header, records = r.header, list(r)
    odd = BamRecord(
        qname="odd0", flag=0, ref_id=0, pos=150, mapq=60,
        cigar=[(CMATCH, 30)], next_ref_id=-1, next_pos=-1,
        seq="A" * 30, qual=bytes([30] * 30),
    )
    odd.set_tag("MI", "999", "Z")
    records.append(odd)
    records.sort(key=lambda rec: (rec.ref_id, rec.pos))
    inp2 = str(tmp / "with_odd.bam")
    with BamWriter(inp2, header) as w:
        w.write_all(records)

    outs = {}
    for label, extra in (("pass", ["--passthrough"]), ("drop", [])):
        out = str(tmp / f"odd_{label}.bam")
        cp = _run_tool(
            "call_duplex_consensus_tpu.py",
            ["-i", inp2, "-o", out, "--reference", fasta, *extra],
        )
        assert cp.returncode == 0, cp.stderr[-2000:]
        with BamReader(out) as r:
            outs[label] = [rec.qname for rec in r]
    assert "odd0" in outs["pass"]
    assert "odd0" not in outs["drop"]
    assert len(outs["pass"]) == len(outs["drop"]) + 1


def test_run_entry_with_reference_style_config(tmp_path):
    """`python -m bsseqconsensusreads_tpu run --config config.yaml --bam …`
    — the snakemake-invocation equivalent (README.md:62) driven by a
    reference-style config.yaml (config.yaml:1-11 keys + promoted knobs)."""
    rng = np.random.default_rng(79)
    name, genome = random_genome(rng, 6000)
    write_fasta(str(tmp_path / "genome.fa"), name, genome)
    header, records = make_grouped_bam_records(rng, name, genome, n_families=6)
    inp = str(tmp_path / "sample.bam")
    with BamWriter(inp, header) as w:
        w.write_all(records)
    cfg = tmp_path / "config.yaml"
    cfg.write_text(
        f"genome_dir: {tmp_path}\n"
        "genome_fasta_file_name: genome.fa\n"
        f"tmp: {tmp_path}\n"
        "backend: cpu\n"
        "aligner: self\n"
    )
    env = dict(os.environ, PYTHONPATH=REPO, BSSEQ_TPU_BACKEND="cpu")
    cp = subprocess.run(
        [sys.executable, "-m", "bsseqconsensusreads_tpu", "run",
         "--config", str(cfg), "--bam", inp,
         "--outdir", str(tmp_path / "output")],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    assert cp.returncode == 0, cp.stderr[-2000:]
    outs = os.listdir(tmp_path / "output")
    finals = [f for f in outs if f.endswith(".bam")]
    assert finals, outs


def test_group_dropin_chains_into_molecular(tmp_path):
    """The fgbio GroupReadsByUmi rule shape, chained the way Snakemake
    would: `group_reads_by_umi_tpu.py -s paired -e 1` producing the
    reference's input contract (README.md:51-55), then the molecular
    drop-in consuming it."""
    from tests.test_group_umi import make_raw_duplex_records

    rng = np.random.default_rng(80)
    name, genome = random_genome(rng, 6000)
    header, records, truth = make_raw_duplex_records(
        rng, name, genome, n_families=5
    )
    raw = str(tmp_path / "raw.bam")
    with BamWriter(raw, header) as w:
        w.write_all(records)

    grouped = str(tmp_path / "grouped.bam")
    cp = _run_tool(
        "group_reads_by_umi_tpu.py",
        ["-s", "paired", "-e", "1", "-i", raw, "-o", grouped],
    )
    assert cp.returncode == 0, cp.stderr[-2000:]
    assert '"molecules"' in cp.stderr  # stats JSON on stderr

    with BamReader(grouped) as r:
        assert "SO:unsorted" in r.header.text
        back = list(r)
    fams = {}
    for rec in back:
        fams.setdefault(str(rec.get_tag("MI")).split("/")[0], set()).add(rec.qname)
    truth_fams = {}
    for q, (fam, _s) in truth.items():
        truth_fams.setdefault(fam, set()).add(q)
    assert {frozenset(v) for v in fams.values()} == {
        frozenset(v) for v in truth_fams.values()
    }

    consensus = str(tmp_path / "consensus.bam")
    cp = _run_tool(
        "call_molecular_consensus_tpu.py",
        ["-i", grouped, "-o", consensus, "--grouping", "adjacent"],
    )
    assert cp.returncode == 0, cp.stderr[-2000:]
    n_strand_families = len({(f, s) for f, s in truth.values()})
    with BamReader(consensus) as r:
        assert sum(1 for _ in r) == 2 * n_strand_families


def test_filter_consensus_dropin_subprocess(molecular_input, tmp_path):
    """The FilterConsensusReads rule shape: molecular drop-in output
    piped through `filter_consensus_reads_tpu.py -M …` as Snakemake
    would chain them."""
    tmp, inp = molecular_input
    consensus = str(tmp_path / "consensus.bam")
    cp = _run_tool("call_molecular_consensus_tpu.py", ["-i", inp, "-o", consensus])
    assert cp.returncode == 0, cp.stderr[-2000:]

    filtered = str(tmp_path / "filtered.bam")
    cp = _run_tool(
        "filter_consensus_reads_tpu.py",
        ["-i", consensus, "-o", filtered, "-M", "1",
         "-E", "1.0", "-e", "1.0", "-N", "0", "-n", "1.0"],
    )
    assert cp.returncode == 0, cp.stderr[-2000:]
    assert '"kept_records"' in cp.stderr
    with BamReader(consensus) as a, BamReader(filtered) as b:
        na, nb = sum(1 for _ in a), sum(1 for _ in b)
    assert na == nb > 0  # permissive thresholds keep everything

    strict = str(tmp_path / "strict.bam")
    cp = _run_tool(
        "filter_consensus_reads_tpu.py",
        ["-i", consensus, "-o", strict, "-M", "50"],
    )
    assert cp.returncode == 0, cp.stderr[-2000:]
    with BamReader(strict) as r:
        assert sum(1 for _ in r) == 0


def test_full_user_journey_via_dropins(tmp_path):
    """The complete fgbio-free journey, every step a subprocess drop-in
    the way Snakemake rule bodies would chain them: raw aligned BAM ->
    group -> metrics -> molecular consensus -> filter."""
    import json

    from tests.test_group_umi import make_raw_duplex_records

    rng = np.random.default_rng(81)
    name, genome = random_genome(rng, 8000)
    header, records, truth = make_raw_duplex_records(
        rng, name, genome, n_families=6, reads_per_strand=(3, 4)
    )
    raw = str(tmp_path / "raw.bam")
    with BamWriter(raw, header) as w:
        w.write_all(records)
    n_families = len({f for f, _ in truth.values()})

    grouped = str(tmp_path / "grouped.bam")
    cp = _run_tool("group_reads_by_umi_tpu.py",
                   ["-s", "paired", "-e", "1", "-i", raw, "-o", grouped])
    assert cp.returncode == 0, cp.stderr[-2000:]

    cp = subprocess.run(
        [sys.executable, "-m", "bsseqconsensusreads_tpu", "metrics",
         "-i", grouped, "--compact"],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, PYTHONPATH=REPO, BSSEQ_TPU_BACKEND="cpu"),
        cwd=REPO,
    )
    assert cp.returncode == 0, cp.stderr[-2000:]
    m = json.loads(cp.stdout.strip().splitlines()[-1])
    assert m["molecules"] == n_families and m["duplex_fraction"] == 1.0

    consensus = str(tmp_path / "consensus.bam")
    cp = _run_tool("call_molecular_consensus_tpu.py",
                   ["-i", grouped, "-o", consensus, "--grouping", "adjacent"])
    assert cp.returncode == 0, cp.stderr[-2000:]

    filtered = str(tmp_path / "filtered.bam")
    cp = _run_tool("filter_consensus_reads_tpu.py",
                   ["-i", consensus, "-o", filtered, "-M", "2",
                    "-E", "1.0", "-e", "1.0", "-N", "0", "-n", "1.0"])
    assert cp.returncode == 0, cp.stderr[-2000:]
    with BamReader(filtered) as r:
        kept = list(r)
    # every strand family simulated at depth >= 3 survives -M 2: R1+R2 per
    # strand family
    assert len(kept) == 2 * 2 * n_families
    assert all(rec.has_tag("MI") and rec.has_tag("cD") for rec in kept)
