"""Multi-device sharding tests on the 8-device virtual CPU mesh."""

import numpy as np
import pytest

import jax

from bsseqconsensusreads_tpu.alphabet import NBASE
from bsseqconsensusreads_tpu.models.duplex import duplex_call_pipeline
from bsseqconsensusreads_tpu.models.molecular import molecular_consensus
from bsseqconsensusreads_tpu.models.params import ConsensusParams
from bsseqconsensusreads_tpu.ops.encode import encode_duplex_families, iter_mi_groups
from bsseqconsensusreads_tpu.parallel import (
    deep_family_consensus,
    default_mesh,
    make_mesh,
    pad_families,
    sharded_duplex_pipeline,
    sharded_molecular_consensus,
)
from bsseqconsensusreads_tpu.utils.testing import (
    make_aligned_duplex_group,
    random_genome,
)


def tree_equal(a, b):
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]), err_msg=k)


@pytest.fixture(scope="module")
def eight_devices():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return jax.devices()


class TestMesh:
    def test_default_mesh_all_data(self, eight_devices):
        mesh = default_mesh()
        assert mesh.shape == {"data": 8, "reads": 1}

    def test_2d_mesh(self, eight_devices):
        mesh = make_mesh(n_data=4, n_reads=2)
        assert mesh.shape == {"data": 4, "reads": 2}

    def test_mesh_too_big_raises(self, eight_devices):
        with pytest.raises(ValueError, match="needs"):
            make_mesh(n_data=16, n_reads=2)

    def test_pad_families(self):
        arrs = (
            np.ones((5, 3), np.int8),
            np.ones((5, 2), np.float32),
            np.ones(5, bool),
        )
        (a, b, c), n = pad_families(arrs, 5, 4)
        assert n == 8
        assert a.shape == (8, 3) and (a[5:] == NBASE).all()
        assert b.shape == (8, 2) and (b[5:] == 0).all()
        assert c.shape == (8,) and (~c[5:]).all()


class TestShardedMolecular:
    def test_matches_unsharded(self, eight_devices):
        rng = np.random.default_rng(41)
        params = ConsensusParams()
        F, T, W = 16, 6, 128
        bases = rng.integers(0, 4, size=(F, T, 2, W)).astype(np.int8)
        bases[rng.random(bases.shape) < 0.2] = NBASE
        quals = rng.integers(2, 41, size=bases.shape).astype(np.uint8)
        mesh = default_mesh()
        sharded = sharded_molecular_consensus(mesh, params)
        got = sharded(bases, quals)
        want = molecular_consensus(bases, quals, params)
        tree_equal(got, want)

    def test_with_family_padding(self, eight_devices):
        rng = np.random.default_rng(42)
        params = ConsensusParams()
        F = 5  # not divisible by 8
        bases = rng.integers(0, 4, size=(F, 4, 2, 128)).astype(np.int8)
        quals = rng.integers(2, 41, size=bases.shape).astype(np.uint8)
        (pb, pq), padded_n = pad_families((bases, quals), F, 8)
        mesh = default_mesh()
        out = sharded_molecular_consensus(mesh, params)(pb, pq)
        want = molecular_consensus(bases, quals, params)
        got = {k: np.asarray(v)[:F] for k, v in out.items()}
        tree_equal(got, want)
        # pad families decode to all-no-call
        assert (np.asarray(out["base"])[F:] == NBASE).all()


class TestDeepFamilySplit:
    def test_segmented_reduction_matches_unsharded(self, eight_devices):
        rng = np.random.default_rng(43)
        params = ConsensusParams()
        F, T, W = 4, 64, 128  # T split over 2 devices
        bases = rng.integers(0, 4, size=(F, T, 2, W)).astype(np.int8)
        bases[rng.random(bases.shape) < 0.3] = NBASE
        quals = rng.integers(2, 41, size=bases.shape).astype(np.uint8)
        mesh = make_mesh(n_data=4, n_reads=2)
        deep = deep_family_consensus(mesh, params)
        got = deep(bases, quals)
        want = molecular_consensus(bases, quals, params)
        for k in ("base", "depth", "errors"):
            np.testing.assert_array_equal(np.asarray(got[k]), np.asarray(want[k]), err_msg=k)
        # float reduction order differs across shards: quals within 1
        dq = np.abs(
            np.asarray(got["qual"], np.int32) - np.asarray(want["qual"], np.int32)
        )
        assert dq.max() <= 1

    def test_wide_reads_axis(self, eight_devices):
        rng = np.random.default_rng(44)
        params = ConsensusParams(consensus_call_overlapping_bases=False)
        F, T, W = 1, 512, 128  # one deep family over all 8 devices
        bases = rng.integers(0, 4, size=(F, T, 2, W)).astype(np.int8)
        quals = rng.integers(2, 41, size=bases.shape).astype(np.uint8)
        mesh = make_mesh(n_data=1, n_reads=8)
        got = deep_family_consensus(mesh, params)(bases, quals)
        want = molecular_consensus(bases, quals, params)
        np.testing.assert_array_equal(np.asarray(got["base"]), np.asarray(want["base"]))
        np.testing.assert_array_equal(np.asarray(got["depth"]), np.asarray(want["depth"]))


class TestShardedDuplex:
    def test_matches_unsharded(self, eight_devices):
        rng = np.random.default_rng(45)
        name, genome = random_genome(rng, 3000)
        recs = []
        for mi in range(8):
            recs += make_aligned_duplex_group(rng, name, genome, mi, 30 + mi * 150, 80)
        groups = iter_mi_groups(recs, strip_suffix=True)
        batch, _, _ = encode_duplex_families(groups, lambda n, s, e: genome[s:e], [name])
        params = ConsensusParams(min_reads=0)
        mesh = default_mesh()
        sharded = sharded_duplex_pipeline(mesh, params)
        got = sharded(
            batch.bases, batch.quals, batch.cover, batch.ref,
            batch.convert_mask, batch.extend_eligible,
        )
        want = duplex_call_pipeline(
            batch.bases, batch.quals, batch.cover, batch.ref,
            batch.convert_mask, batch.extend_eligible, params=params,
        )
        tree_equal(got, want)


class TestProductionMeshDispatch:
    """The round-2 VERDICT item: the production callers must use the mesh
    when >1 device is visible, produce byte-identical output to the
    single-device run, and route deep families instead of skipping them."""

    def _pipeline_bams(self, tmp_path, mesh_mode):
        import os

        from bsseqconsensusreads_tpu.io.bam import BamHeader, BamReader, BamWriter
        from bsseqconsensusreads_tpu.pipeline.calling import (
            StageStats,
            call_duplex_batches,
            call_molecular_batches,
        )
        from bsseqconsensusreads_tpu.utils.testing import (
            make_grouped_bam_records,
            write_fasta,
        )
        from bsseqconsensusreads_tpu.io.fasta import FastaFile

        rng = np.random.default_rng(77)
        name, genome = random_genome(rng, 5000)
        fasta = str(tmp_path / f"g_{mesh_mode}.fa")
        write_fasta(fasta, name, genome)
        header, records = make_grouped_bam_records(
            rng, name, genome, n_families=17, error_rate=0.01
        )
        mesh = "auto" if mesh_mode == "mesh" else None
        stats = StageStats()
        mol = [
            rec
            for b in call_molecular_batches(
                records, mode="self", grouping="coordinate", stats=stats,
                mesh=mesh,
            )
            for rec in b
        ]
        fa = FastaFile(fasta)
        dup = [
            rec
            for b in call_duplex_batches(
                iter(mol), fa.fetch, [name], mode="self",
                grouping="coordinate", mesh=mesh,
            )
            for rec in b
        ]
        out = str(tmp_path / f"out_{mesh_mode}.bam")
        with BamWriter(out, header) as w:
            w.write_all(dup)
        return out

    def test_mesh_run_byte_identical_to_single_device(self, tmp_path, eight_devices):
        a = self._pipeline_bams(tmp_path, "mesh")
        b = self._pipeline_bams(tmp_path, "single")
        import gzip

        assert gzip.decompress(open(a, "rb").read()) == gzip.decompress(
            open(b, "rb").read()
        )

    @pytest.mark.parametrize("mesh_mode", ["mesh", "single"])
    def test_deep_family_routed_not_skipped(self, mesh_mode, eight_devices):
        from bsseqconsensusreads_tpu.io.bam import BamRecord, CMATCH
        from bsseqconsensusreads_tpu.pipeline.calling import (
            StageStats,
            call_molecular_batches,
        )

        rng = np.random.default_rng(78)
        name, genome = random_genome(rng, 400)
        depth = 40  # > deep_threshold below -> routed to the deep path
        recs = []
        for d in range(depth):
            for flag, pos in ((99, 50), (147, 90)):
                r = BamRecord(
                    qname=f"t{d}", flag=flag, ref_id=0, pos=pos, mapq=60,
                    cigar=[(CMATCH, 40)], next_ref_id=0,
                    next_pos=90 if flag == 99 else 50,
                    seq=genome[pos : pos + 40], qual=bytes([30] * 40),
                )
                r.set_tag("MI", "0/A", "Z")
                r.set_tag("RX", "AC-GT", "Z")
                recs.append(r)
        stats = StageStats()
        mesh = "auto" if mesh_mode == "mesh" else None
        out = [
            rec
            for b in call_molecular_batches(
                iter(recs), mode="self", grouping="adjacent", stats=stats,
                mesh=mesh, deep_threshold=16,
            )
            for rec in b
        ]
        # the deep family is emitted, not skipped
        assert stats.skipped_families == 0
        assert stats.families == 1
        assert len(out) == 2  # R1 + R2 consensus
        for rec in out:
            assert rec.get_tag("cD") == depth
            assert rec.seq == genome[rec.pos : rec.pos + 40]


class TestShardedMolecularPacked:
    def test_wire_roundtrip_matches_dict_path(self, eight_devices):
        from bsseqconsensusreads_tpu.models.molecular import (
            packed_molecular_kernel,
            unpack_molecular_outputs,
        )
        from bsseqconsensusreads_tpu.parallel import sharded_molecular_packed

        rng = np.random.default_rng(44)
        params = ConsensusParams()
        F, T, W = 16, 6, 128
        bases = rng.integers(0, 4, size=(F, T, 2, W)).astype(np.int8)
        bases[rng.random(bases.shape) < 0.2] = NBASE
        quals = rng.integers(2, 41, size=bases.shape).astype(np.uint8)
        want = {
            k: np.asarray(v)
            for k, v in molecular_consensus(bases, quals, params).items()
        }

        # single-device packed wire
        wire = packed_molecular_kernel()(bases, quals, params)
        got = unpack_molecular_outputs(np.asarray(wire), f=F, w=W)
        assert set(got) == set(want)
        for k in want:
            np.testing.assert_array_equal(got[k], want[k], err_msg=k)
            assert got[k].dtype == want[k].dtype, k

        # sharded packed wire: per-device packs must concatenate into the
        # same family-major layout as the single-device pack
        mesh = default_mesh()
        swire = sharded_molecular_packed(mesh, params)(bases, quals)
        np.testing.assert_array_equal(np.asarray(swire), np.asarray(wire))

    def test_wide_depth_survives_byte_planes(self, eight_devices):
        # depths > 255 exercise the u16 hi byte plane
        from bsseqconsensusreads_tpu.models.molecular import (
            packed_molecular_kernel,
            unpack_molecular_outputs,
        )

        params = ConsensusParams()
        F, T, W = 2, 300, 32
        bases = np.zeros((F, T, 2, W), np.int8)  # all 'A', depth = 300
        quals = np.full(bases.shape, 30, np.uint8)
        wire = packed_molecular_kernel()(bases, quals, params)
        got = unpack_molecular_outputs(np.asarray(wire), f=F, w=W)
        assert (got["depth"] == T).all()
        assert (got["errors"] == 0).all()
