// Native hot path for the host<->device wire formats (ops/wire.py).
//
// The tunnel-bound duplex stage moves ~10M cells per batch each way; the
// numpy pack (nibble merge + qual codebook detection + 2-bit index packing)
// costs ~130 ms/batch and the output unpack ~20 ms — all host time that
// serializes with the device transfer. This file is the single-sweep C++
// equivalent: one pass builds the nibble plane, the covered-qual histogram,
// and the meta bytes; a second pass (codebook modes) emits the packed qual
// indices. Byte-for-byte identical to the numpy reference implementation in
// bsseqconsensusreads_tpu/ops/wire.py (tests/test_wirepack.py asserts it).
//
// Role in the reference design: the reference serializes between stages via
// BAM files and pysam/htslib C loops (SURVEY.md section 3.1); this is the
// TPU framework's equivalent native serialization layer, sized for the
// device tunnel instead of the filesystem.

#include <cstdint>
#include <cstring>

namespace {

// Error codes mirrored by the Python wrapper (io/wirepack.py).
constexpr int kErrTooManyLevels = -2;  // explicit mode, levels overflow book
constexpr int kErrQualTooHigh = -3;    // covered qual > 93 (BAM printable max)
constexpr int kErrBadMode = -4;

inline int resolve_auto(int nlevels, bool has_255, int max_level) {
  if (nlevels > 16 || has_255 || max_level > 93) return 8;
  return nlevels <= 4 ? 2 : 4;
}

}  // namespace

extern "C" {

// Pack the duplex input batch. Arrays are C-contiguous:
//   bases  int8  [f*r*w]   (framework codes, NBASE=4 where uncovered)
//   quals  uint8 [f*r*w]
//   cover  uint8 [f*r*w]   (0/1)
//   cmask  uint8 [f*r]     (0/1 convert_mask rows)
//   elig   uint8 [f]       (0/1 extend_eligible)
// mode: 8 (raw), 4, 2, or 0 = auto (smallest codebook that fits).
// Outputs:
//   nib_out  uint8 [cells/2]           cell0 low nibble, cell1 high
//   meta_out uint8 [f]                 cmask bits 0..3 | elig << 4
//   qual_out uint8 [>= cells + 16]     q8: raw bytes; q2/q4: codebook
//            (2^bits bytes) ++ packed indices, zero-padded to u32 words
//   qual_len_out -> bytes written to qual_out (word-aligned)
//   nlevels_out  -> distinct covered qual values found (0 if q8 fast path)
// Returns resolved bits (8/4/2) or a negative error code.
int wirepack_pack_duplex(const int8_t* bases, const uint8_t* quals,
                         const uint8_t* cover, const uint8_t* cmask,
                         const uint8_t* elig, int64_t f, int64_t r, int64_t w,
                         int mode, uint8_t* nib_out, uint8_t* meta_out,
                         uint8_t* qual_out, int64_t* qual_len_out,
                         int* nlevels_out) {
  if (mode != 0 && mode != 2 && mode != 4 && mode != 8) return kErrBadMode;
  const int64_t cells = f * r * w;
  const int64_t rows4 = r < 4 ? r : 4;

  // Sweep 1: nibble plane + covered-qual histogram (skipped for plain q8,
  // where levels are never consulted).
  int64_t hist[256];
  const bool need_hist = mode != 8;
  if (need_hist) std::memset(hist, 0, sizeof(hist));
  for (int64_t i = 0; i < cells; i += 2) {
    const uint8_t c0 = cover[i] ? 1 : 0, c1 = cover[i + 1] ? 1 : 0;
    const uint8_t n0 = (uint8_t(bases[i]) & 0x7) | uint8_t(c0 << 3);
    const uint8_t n1 = (uint8_t(bases[i + 1]) & 0x7) | uint8_t(c1 << 3);
    nib_out[i >> 1] = uint8_t(n0 | (n1 << 4));
    if (need_hist) {
      if (c0) hist[quals[i]]++;
      if (c1) hist[quals[i + 1]]++;
    }
  }

  // Meta bytes: convert_mask rows 0..3 then eligible bit 4.
  for (int64_t fam = 0; fam < f; ++fam) {
    uint8_t m = 0;
    for (int64_t row = 0; row < rows4; ++row)
      m |= uint8_t((cmask[fam * r + row] ? 1 : 0) << row);
    m |= uint8_t((elig[fam] ? 1 : 0) << 4);
    meta_out[fam] = m;
  }

  // Codebook from the histogram (matching ops/wire._qual_levels: empty ->
  // single level 0; covered 255 flagged separately).
  uint8_t levels[256];
  int nlevels = 0;
  bool has_255 = false;
  int max_level = 0;
  if (need_hist) {
    for (int v = 0; v < 255; ++v)
      if (hist[v]) {
        levels[nlevels++] = uint8_t(v);
        max_level = v;
      }
    has_255 = hist[255] != 0;
    if (nlevels == 0) {
      levels[0] = 0;
      nlevels = 1;
      max_level = 0;
    }
  }
  if (nlevels_out) *nlevels_out = nlevels;

  int bits = mode;
  if (mode == 0) bits = resolve_auto(nlevels, has_255, max_level);
  if (bits == 2 || bits == 4) {
    if (has_255 || max_level > 93) return kErrQualTooHigh;
    if (nlevels > (1 << bits)) return kErrTooManyLevels;
  }

  if (bits == 8) {
    std::memcpy(qual_out, quals, size_t(cells));
    int64_t len = cells;
    while (len & 3) qual_out[len++] = 0;
    *qual_len_out = len;
    return 8;
  }

  // Codebook section: 2^bits bytes, unfilled entries zero.
  const int book = 1 << bits;
  std::memset(qual_out, 0, size_t(book));
  std::memcpy(qual_out, levels, size_t(nlevels));
  uint8_t lut[256];
  std::memset(lut, 0, sizeof(lut));
  for (int i = 0; i < nlevels; ++i) lut[levels[i]] = uint8_t(i);

  // Sweep 2: pack qual indices little-bit-endian within each byte
  // (index of cell j occupies bits [bits*j % 8, ...)); uncovered cells
  // carry index 0 — matching _pack_qual_codes' sentinel->0 LUT.
  uint8_t* dst = qual_out + book;
  const int per = 8 / bits;
  int64_t nbytes = (cells + per - 1) / per;
  if (bits == 2) {
    int64_t i = 0, b = 0;
    const int64_t full = cells / 4;
    for (; b < full; ++b, i += 4) {
      const uint8_t i0 = cover[i] ? lut[quals[i]] : 0;
      const uint8_t i1 = cover[i + 1] ? lut[quals[i + 1]] : 0;
      const uint8_t i2 = cover[i + 2] ? lut[quals[i + 2]] : 0;
      const uint8_t i3 = cover[i + 3] ? lut[quals[i + 3]] : 0;
      dst[b] = uint8_t(i0 | (i1 << 2) | (i2 << 4) | (i3 << 6));
    }
    if (i < cells) {
      uint8_t acc = 0;
      for (int s = 0; i < cells; ++i, ++s)
        acc |= uint8_t((cover[i] ? lut[quals[i]] : 0) << (2 * s));
      dst[b++] = acc;
    }
  } else {  // bits == 4
    int64_t i = 0, b = 0;
    const int64_t full = cells / 2;
    for (; b < full; ++b, i += 2) {
      const uint8_t i0 = cover[i] ? lut[quals[i]] : 0;
      const uint8_t i1 = cover[i + 1] ? lut[quals[i + 1]] : 0;
      dst[b] = uint8_t(i0 | (i1 << 4));
    }
    if (i < cells) dst[b++] = cover[i] ? lut[quals[i]] : 0;
  }
  while (nbytes & 3) dst[nbytes++] = 0;
  *qual_len_out = book + nbytes;
  return bits;
}

// Unpack the family-major planar duplex output wire
// (models/duplex.pack_duplex_outputs): wire uint8 [f, 4, w] — per family,
// rows 0-1 = byte0 planes of duplex R1/R2
// (base(3b)|depth(2b)<<3|errors(2b)<<5|a_depth(1b)<<7), rows 2-3 = the
// consensus qual planes. Fills six C-contiguous [f*2*w] arrays.
void wirepack_unpack_duplex_outputs(const uint8_t* wire, int64_t f, int64_t w,
                                    int8_t* base, uint8_t* qual,
                                    int16_t* depth, int16_t* errors,
                                    int8_t* a_depth, int8_t* b_depth) {
  for (int64_t fam = 0; fam < f; ++fam) {
    const uint8_t* plane_b = wire + fam * 4 * w;
    const uint8_t* plane_q = plane_b + 2 * w;
    const int64_t out0 = fam * 2 * w;
    for (int64_t i = 0; i < 2 * w; ++i) {
      const uint8_t b0 = plane_b[i];
      const int16_t d = int16_t((b0 >> 3) & 0x3);
      const int8_t a = int8_t((b0 >> 7) & 0x1);
      base[out0 + i] = int8_t(b0 & 0x7);
      qual[out0 + i] = plane_q[i];
      depth[out0 + i] = d;
      errors[out0 + i] = int16_t((b0 >> 5) & 0x3);
      a_depth[out0 + i] = a;
      b_depth[out0 + i] = int8_t(d - a);
    }
  }
}

}  // extern "C"
