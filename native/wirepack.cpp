// Native hot path for the host<->device wire formats (ops/wire.py).
//
// The tunnel-bound duplex stage moves ~10M cells per batch each way; the
// numpy pack (nibble merge + qual codebook detection + 2-bit index packing)
// costs ~130 ms/batch and the output unpack ~20 ms — all host time that
// serializes with the device transfer. This file is the single-sweep C++
// equivalent: one pass builds the nibble plane, the covered-qual histogram,
// and the meta bytes; a second pass (codebook modes) emits the packed qual
// indices. Byte-for-byte identical to the numpy reference implementation in
// bsseqconsensusreads_tpu/ops/wire.py (tests/test_wirepack.py asserts it).
//
// Role in the reference design: the reference serializes between stages via
// BAM files and pysam/htslib C loops (SURVEY.md section 3.1); this is the
// TPU framework's equivalent native serialization layer, sized for the
// device tunnel instead of the filesystem.

#include <algorithm>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

// ---- BAM record serialization constants (mirror io/bam.py) ----

// framework base code (A=0 C=1 G=2 T=3 N=4) -> SAM nt16 nibble
constexpr uint8_t kNt16[5] = {1, 2, 4, 8, 15};
// complement in framework code space (A<->T, C<->G, N->N)
constexpr uint8_t kComp[5] = {3, 2, 1, 0, 4};

constexpr uint16_t kPaired = 0x1, kProperPair = 0x2, kUnmap = 0x4,
                   kMUnmap = 0x8, kReverse = 0x10, kMReverse = 0x20,
                   kRead1 = 0x40, kRead2 = 0x80;

// BAI binning, SAM spec section 5.3 (identical to io/bam.py reg2bin)
inline uint16_t reg2bin(int64_t beg, int64_t end) {
  --end;
  if (end < 0) end = 0;
  if (beg < 0) beg = 0;
  if (beg >> 14 == end >> 14) return uint16_t(((1 << 15) - 1) / 7 + (beg >> 14));
  if (beg >> 17 == end >> 17) return uint16_t(((1 << 12) - 1) / 7 + (beg >> 17));
  if (beg >> 20 == end >> 20) return uint16_t(((1 << 9) - 1) / 7 + (beg >> 20));
  if (beg >> 23 == end >> 23) return uint16_t(((1 << 6) - 1) / 7 + (beg >> 23));
  if (beg >> 26 == end >> 26) return uint16_t(((1 << 3) - 1) / 7 + (beg >> 26));
  return 0;
}

struct Cursor {
  uint8_t* p;
  const uint8_t* end;
  bool overflow = false;

  inline void need(int64_t n) {
    if (p + n > end) overflow = true;
  }
  inline void put_bytes(const void* src, int64_t n) {
    need(n);
    if (!overflow) std::memcpy(p, src, size_t(n));
    p += n;
  }
  inline void put_u8(uint8_t v) { put_bytes(&v, 1); }
  inline void put_u16(uint16_t v) { put_bytes(&v, 2); }
  inline void put_i32(int32_t v) { put_bytes(&v, 4); }
  inline void put_u32(uint32_t v) { put_bytes(&v, 4); }
  inline void put_f32(float v) { put_bytes(&v, 4); }
};

inline void put_int_tag(Cursor& c, const char* key, int32_t v) {
  c.put_bytes(key, 2);
  c.put_u8('i');
  c.put_i32(v);
}

// B:S (uint16) array tag from int16/int8 sources; `flip` writes the
// values reversed (per-base tags follow the emitted SEQ orientation —
// reverse-complemented records in unaligned mode store reversed arrays,
// mirroring pipeline.calling._consensus_tags)
template <typename T>
inline void put_arr_tag(Cursor& c, const char* key, const T* vals,
                        int64_t n, bool flip = false) {
  c.put_bytes(key, 2);
  c.put_u8('B');
  c.put_u8('S');
  c.put_u32(uint32_t(n));
  if (flip) {
    for (int64_t i = n - 1; i >= 0; --i) c.put_u16(uint16_t(vals[i]));
  } else {
    for (int64_t i = 0; i < n; ++i) c.put_u16(uint16_t(vals[i]));
  }
}

// Error codes mirrored by the Python wrapper (io/wirepack.py).
constexpr int kErrTooManyLevels = -2;  // explicit mode, levels overflow book
constexpr int kErrQualTooHigh = -3;    // covered qual > 93 (BAM printable max)
constexpr int kErrBadMode = -4;
constexpr int kErrQnameTooLong = -5;   // BAM l_read_name is a uint8

inline int resolve_auto(int nlevels, bool has_255, int max_level) {
  if (nlevels > 16 || has_255 || max_level > 93) return 8;
  return nlevels <= 4 ? 2 : 4;
}

}  // namespace

extern "C" {

// Pack the duplex input batch. Arrays are C-contiguous:
//   bases  int8  [f*r*w]   (framework codes, NBASE=4 where uncovered)
//   quals  uint8 [f*r*w]
//   cover  uint8 [f*r*w]   (0/1)
//   cmask  uint8 [f*r]     (0/1 convert_mask rows)
//   elig   uint8 [f]       (0/1 extend_eligible)
// mode: 8 (raw), 4, 2, or 0 = auto (smallest codebook that fits).
// Outputs:
//   nib_out  uint8 [cells/2]           cell0 low nibble, cell1 high
//   meta_out uint8 [f]                 cmask bits 0..3 | elig << 4
//   qual_out uint8 [>= cells + 16]     q8: raw bytes; q2/q4: codebook
//            (2^bits bytes) ++ packed indices, zero-padded to u32 words
//   qual_len_out -> bytes written to qual_out (word-aligned)
//   nlevels_out  -> distinct covered qual values found (0 if q8 fast path)
// Returns resolved bits (8/4/2) or a negative error code.
int wirepack_pack_duplex(const int8_t* bases, const uint8_t* quals,
                         const uint8_t* cover, const uint8_t* cmask,
                         const uint8_t* elig, int64_t f, int64_t r, int64_t w,
                         int mode, uint8_t* nib_out, uint8_t* meta_out,
                         uint8_t* qual_out, int64_t* qual_len_out,
                         int* nlevels_out) {
  if (mode != 0 && mode != 2 && mode != 4 && mode != 8) return kErrBadMode;
  const int64_t cells = f * r * w;
  const int64_t rows4 = r < 4 ? r : 4;

  // Sweep 1: nibble plane + covered-qual histogram (skipped for plain q8,
  // where levels are never consulted).
  int64_t hist[256];
  const bool need_hist = mode != 8;
  if (need_hist) std::memset(hist, 0, sizeof(hist));
  for (int64_t i = 0; i < cells; i += 2) {
    const uint8_t c0 = cover[i] ? 1 : 0, c1 = cover[i + 1] ? 1 : 0;
    const uint8_t n0 = (uint8_t(bases[i]) & 0x7) | uint8_t(c0 << 3);
    const uint8_t n1 = (uint8_t(bases[i + 1]) & 0x7) | uint8_t(c1 << 3);
    nib_out[i >> 1] = uint8_t(n0 | (n1 << 4));
    if (need_hist) {
      if (c0) hist[quals[i]]++;
      if (c1) hist[quals[i + 1]]++;
    }
  }

  // Meta bytes: convert_mask rows 0..3 then eligible bit 4.
  for (int64_t fam = 0; fam < f; ++fam) {
    uint8_t m = 0;
    for (int64_t row = 0; row < rows4; ++row)
      m |= uint8_t((cmask[fam * r + row] ? 1 : 0) << row);
    m |= uint8_t((elig[fam] ? 1 : 0) << 4);
    meta_out[fam] = m;
  }

  // Codebook from the histogram (matching ops/wire._qual_levels: empty ->
  // single level 0; covered 255 flagged separately).
  uint8_t levels[256];
  int nlevels = 0;
  bool has_255 = false;
  int max_level = 0;
  if (need_hist) {
    for (int v = 0; v < 255; ++v)
      if (hist[v]) {
        levels[nlevels++] = uint8_t(v);
        max_level = v;
      }
    has_255 = hist[255] != 0;
    if (nlevels == 0) {
      levels[0] = 0;
      nlevels = 1;
      max_level = 0;
    }
  }
  if (nlevels_out) *nlevels_out = nlevels;

  int bits = mode;
  if (mode == 0) bits = resolve_auto(nlevels, has_255, max_level);
  if (bits == 2 || bits == 4) {
    if (has_255 || max_level > 93) return kErrQualTooHigh;
    if (nlevels > (1 << bits)) return kErrTooManyLevels;
  }

  if (bits == 8) {
    std::memcpy(qual_out, quals, size_t(cells));
    int64_t len = cells;
    while (len & 3) qual_out[len++] = 0;
    *qual_len_out = len;
    return 8;
  }

  // Codebook section: 2^bits bytes, unfilled entries zero.
  const int book = 1 << bits;
  std::memset(qual_out, 0, size_t(book));
  std::memcpy(qual_out, levels, size_t(nlevels));
  uint8_t lut[256];
  std::memset(lut, 0, sizeof(lut));
  for (int i = 0; i < nlevels; ++i) lut[levels[i]] = uint8_t(i);

  // Sweep 2: pack qual indices little-bit-endian within each byte
  // (index of cell j occupies bits [bits*j % 8, ...)); uncovered cells
  // carry index 0 — matching _pack_qual_codes' sentinel->0 LUT.
  uint8_t* dst = qual_out + book;
  const int per = 8 / bits;
  int64_t nbytes = (cells + per - 1) / per;
  if (bits == 2) {
    int64_t i = 0, b = 0;
    const int64_t full = cells / 4;
    for (; b < full; ++b, i += 4) {
      const uint8_t i0 = cover[i] ? lut[quals[i]] : 0;
      const uint8_t i1 = cover[i + 1] ? lut[quals[i + 1]] : 0;
      const uint8_t i2 = cover[i + 2] ? lut[quals[i + 2]] : 0;
      const uint8_t i3 = cover[i + 3] ? lut[quals[i + 3]] : 0;
      dst[b] = uint8_t(i0 | (i1 << 2) | (i2 << 4) | (i3 << 6));
    }
    if (i < cells) {
      uint8_t acc = 0;
      for (int s = 0; i < cells; ++i, ++s)
        acc |= uint8_t((cover[i] ? lut[quals[i]] : 0) << (2 * s));
      dst[b++] = acc;
    }
  } else {  // bits == 4
    int64_t i = 0, b = 0;
    const int64_t full = cells / 2;
    for (; b < full; ++b, i += 2) {
      const uint8_t i0 = cover[i] ? lut[quals[i]] : 0;
      const uint8_t i1 = cover[i + 1] ? lut[quals[i + 1]] : 0;
      dst[b] = uint8_t(i0 | (i1 << 4));
    }
    if (i < cells) dst[b++] = cover[i] ? lut[quals[i]] : 0;
  }
  while (nbytes & 3) dst[nbytes++] = 0;
  *qual_len_out = book + nbytes;
  return bits;
}

// Pack segment-packed molecular rows (the ops/wire.py packed wire v2
// body): the nib + qual planes of wirepack_pack_duplex for an [n, 2, w]
// row batch, with cover derived inline (base != NBASE) so the caller
// never materializes the [n, 2, w] cover plane, and no meta section —
// the v2 header planes carry segment ids + row offsets instead of the
// duplex convert/eligible bytes. mode / qual_out sizing / return code
// contract as wirepack_pack_duplex (qual_out needs >= n*2*w + 16 bytes).
int wirepack_pack_rows(const int8_t* bases, const uint8_t* quals,
                       int64_t n, int64_t w, int mode, uint8_t* nib_out,
                       uint8_t* qual_out, int64_t* qual_len_out,
                       int* nlevels_out) {
  if (mode != 0 && mode != 2 && mode != 4 && mode != 8) return kErrBadMode;
  constexpr int8_t kNBase = 4;  // framework "no observation" code
  const int64_t cells = n * 2 * w;

  // Sweep 1: nibble plane + covered-qual histogram, cover on the fly.
  int64_t hist[256];
  const bool need_hist = mode != 8;
  if (need_hist) std::memset(hist, 0, sizeof(hist));
  for (int64_t i = 0; i < cells; i += 2) {
    const uint8_t c0 = bases[i] != kNBase ? 1 : 0;
    const uint8_t c1 = bases[i + 1] != kNBase ? 1 : 0;
    const uint8_t n0 = (uint8_t(bases[i]) & 0x7) | uint8_t(c0 << 3);
    const uint8_t n1 = (uint8_t(bases[i + 1]) & 0x7) | uint8_t(c1 << 3);
    nib_out[i >> 1] = uint8_t(n0 | (n1 << 4));
    if (need_hist) {
      if (c0) hist[quals[i]]++;
      if (c1) hist[quals[i + 1]]++;
    }
  }

  // Codebook resolution: identical to wirepack_pack_duplex.
  uint8_t levels[256];
  int nlevels = 0;
  bool has_255 = false;
  int max_level = 0;
  if (need_hist) {
    for (int v = 0; v < 255; ++v)
      if (hist[v]) {
        levels[nlevels++] = uint8_t(v);
        max_level = v;
      }
    has_255 = hist[255] != 0;
    if (nlevels == 0) {
      levels[0] = 0;
      nlevels = 1;
      max_level = 0;
    }
  }
  if (nlevels_out) *nlevels_out = nlevels;

  int bits = mode;
  if (mode == 0) bits = resolve_auto(nlevels, has_255, max_level);
  if (bits == 2 || bits == 4) {
    if (has_255 || max_level > 93) return kErrQualTooHigh;
    if (nlevels > (1 << bits)) return kErrTooManyLevels;
  }

  if (bits == 8) {
    std::memcpy(qual_out, quals, size_t(cells));
    int64_t len = cells;
    while (len & 3) qual_out[len++] = 0;
    *qual_len_out = len;
    return 8;
  }

  const int book = 1 << bits;
  std::memset(qual_out, 0, size_t(book));
  std::memcpy(qual_out, levels, size_t(nlevels));
  uint8_t lut[256];
  std::memset(lut, 0, sizeof(lut));
  for (int i = 0; i < nlevels; ++i) lut[levels[i]] = uint8_t(i);

  // Sweep 2: packed qual indices, same bit layout as wirepack_pack_duplex
  // (uncovered cells carry index 0 — the sentinel->0 LUT contract).
  uint8_t* dst = qual_out + book;
  const int per = 8 / bits;
  int64_t nbytes = (cells + per - 1) / per;
  int64_t i = 0, b = 0;
  for (; b < cells / per; ++b) {
    uint8_t acc = 0;
    for (int s = 0; s < per; ++s, ++i)
      acc |= uint8_t((bases[i] != kNBase ? lut[quals[i]] : 0) << (bits * s));
    dst[b] = acc;
  }
  if (i < cells) {
    uint8_t acc = 0;
    for (int s = 0; i < cells; ++i, ++s)
      acc |= uint8_t((bases[i] != kNBase ? lut[quals[i]] : 0) << (bits * s));
    dst[b++] = acc;
  }
  while (nbytes & 3) dst[nbytes++] = 0;
  *qual_len_out = book + nbytes;
  return bits;
}

// Emit one consensus batch as ready-to-write BAM record bytes.
//
// The per-record Python path (pipeline.calling._emit_* + io.bam
// encode_record) costs ~50-100 us/record — the production wall once the
// kernel runs on TPU. This is the whole batch in one sweep, byte-identical
// to the Python records (tests/test_recordemit.py diffs them).
//
// Per-column planes, C-contiguous [f, 2, w]:
//   base int8 (framework codes), qual uint8, depth int16, errors int16,
//   a_depth/b_depth int16 or NULL (duplex per-strand tags when present —
//   int16 because raw strand depths from _duplex_rawize exceed int8),
//   a_ss_err/b_ss_err int16 or NULL (per-strand errors vs the strand's
//   OWN call -> aE/bE float rates + ae/be B:S arrays), ss_valid uint8
//   [f, 2] or NULL (per-record gate: covered strands without raw units
//   OMIT the quartet instead of claiming zero errors),
//   bcount uint16 [f, 2, 4, w] or NULL (molecular cB raw base histogram,
//   4 plane-major runs per record), a_call/b_call int8 [f, 2, w] or NULL
//   (duplex per-strand consensus call codes -> ac/bc Z tags).
// Per-family meta:
//   ref_id int32, window_start int64, n_reads int32 (min_reads filter
//   operand), role_reverse uint8 [f, 2],
//   mi/rx string blobs with per-family (offset, len) — rx len 0 = absent.
// mode_self: 1 = aligned self-mode records, 0 = unaligned records.
//
// Returns 0; -1 when out_cap is too small (nothing useful in out); -5 when
// a qname would overflow BAM's uint8 l_read_name (the Python encoder
// raises for the same input — silent truncation would corrupt the record
// stream). n_records/n_skipped report emitted records and
// min_reads-skipped families for StageStats.
// (Symbol versioned _v4: v2 added the cB/ac/bc tag surface, v3 the
// aE/bE/ae/be strand-error surface, v4 its ss_valid gate — a stale built
// library must fail symbol lookup and rebuild, not silently emit the old
// tags.)
int wirepack_emit_consensus_records_v4(
    const int8_t* base, const uint8_t* qual, const int16_t* depth,
    const int16_t* errors, const int16_t* a_depth, const int16_t* b_depth,
    const int16_t* a_ss_err, const int16_t* b_ss_err,
    const uint8_t* ss_valid,
    const uint16_t* bcount, const int8_t* a_call, const int8_t* b_call,
    int64_t f, int64_t w, const int32_t* ref_id, const int64_t* window_start,
    const int32_t* n_reads, const uint8_t* role_reverse,
    const uint8_t* mi_blob, const int32_t* mi_off, const int32_t* mi_len,
    const uint8_t* rx_blob, const int32_t* rx_off, const int32_t* rx_len,
    int min_reads, int mode_self, uint8_t* out, int64_t out_cap,
    int64_t* out_len, int64_t* n_records, int64_t* n_skipped) {
  for (int64_t fi = 0; fi < f; ++fi)
    if (mi_len[fi] + 1 > 255) return kErrQnameTooLong;
  Cursor c{out, out + out_cap};
  int64_t records = 0, skipped = 0;
  // scratch (static cap: w is the bucketed window, <= a few thousand)
  uint8_t* codes = new uint8_t[w];
  uint8_t* rqual = new uint8_t[w];

  for (int64_t fi = 0; fi < f; ++fi) {
    if (n_reads[fi] < min_reads) {
      ++skipped;
      continue;
    }
    // CONTIGUOUS covered span per role, mirroring the Python emitters:
    // interior depth-0 columns emit as N/qual-2 (fgbio no-call semantics)
    // instead of being compacted out, which would shift downstream bases
    // against the single-M-run CIGAR.
    int64_t lo_[2], n_[2];
    int64_t starts[2];
    for (int role = 0; role < 2; ++role) {
      const int16_t* d = depth + (fi * 2 + role) * w;
      int64_t lo = -1, hi = -1;
      for (int64_t i = 0; i < w; ++i)
        if (d[i] > 0) {
          if (lo < 0) lo = i;
          hi = i;
        }
      lo_[role] = lo;
      n_[role] = lo < 0 ? 0 : hi - lo + 1;
      starts[role] = lo < 0 ? -1 : window_start[fi] + lo;
    }
    for (int role = 0; role < 2; ++role) {
      const int64_t n = n_[role];
      if (n == 0) continue;
      const int64_t row = (fi * 2 + role) * w;
      const int64_t lo0 = lo_[role];
      // tlen (same expression as the Python emitters)
      int32_t tlen = 0;
      if (starts[0] >= 0 && starts[1] >= 0) {
        const int64_t lo = starts[0] < starts[1] ? starts[0] : starts[1];
        int64_t hi = 0;
        for (int r2 = 0; r2 < 2; ++r2) {
          const int64_t h = window_start[fi] + lo_[r2] + n_[r2];
          if (h > hi) hi = h;
        }
        tlen = int32_t(starts[role] == lo ? hi - lo : lo - hi);
      }
      const bool reverse = role_reverse[fi * 2 + role] != 0;
      const bool mate_reverse = role_reverse[fi * 2 + (1 - role)] != 0;
      const int64_t mate_pos = starts[1 - role];

      uint16_t flag;
      int32_t rec_ref, rec_pos, rec_next_ref, rec_next_pos, rec_tlen;
      uint8_t mapq;
      uint16_t n_cigar;
      if (mode_self) {
        flag = kPaired | (role ? kRead2 : kRead1);
        if (mate_pos >= 0) {
          flag |= kProperPair;
          if (mate_reverse) flag |= kMReverse;
        } else {
          flag |= kMUnmap;
        }
        if (reverse) flag |= kReverse;
        rec_ref = ref_id[fi];
        rec_pos = int32_t(starts[role]);
        mapq = 60;
        n_cigar = 1;
        rec_next_ref = mate_pos >= 0 ? ref_id[fi] : -1;
        rec_next_pos = int32_t(mate_pos >= 0 ? mate_pos : -1);
        rec_tlen = tlen;
      } else {
        flag = kPaired | kUnmap | kMUnmap | (role ? kRead2 : kRead1);
        rec_ref = -1;
        rec_pos = -1;
        mapq = 0;
        n_cigar = 0;
        rec_next_ref = -1;
        rec_next_pos = -1;
        rec_tlen = 0;
      }

      // base codes + quals in emission orientation
      const bool flip = !mode_self && reverse;
      for (int64_t i = 0; i < n; ++i) {
        const int64_t src = flip ? n - 1 - i : i;
        uint8_t code = uint8_t(base[row + lo0 + src]);
        if (code > 4) code = 4;
        codes[i] = flip ? kComp[code] : code;
        rqual[i] = qual[row + lo0 + src];
      }

      const int32_t l_qname = mi_len[fi] + 1;  // + NUL
      const int64_t body_start_needed =
          4 + 32 + l_qname + 4 * n_cigar + (n + 1) / 2 + n;
      c.need(body_start_needed);  // early bail keeps memcpy ranges valid
      if (c.overflow) break;

      uint8_t* block_size_at = c.p;
      c.p += 4;  // block_size backpatched below
      const int64_t ref_end = mode_self ? starts[role] + n : 1;
      c.put_i32(rec_ref);
      c.put_i32(rec_pos);
      c.put_u8(uint8_t(l_qname));
      c.put_u8(mapq);
      c.put_u16(reg2bin(mode_self ? starts[role] : 0, ref_end));
      c.put_u16(n_cigar);
      c.put_u16(flag);
      c.put_u32(uint32_t(n));
      c.put_i32(rec_next_ref);
      c.put_i32(rec_next_pos);
      c.put_i32(rec_tlen);
      c.put_bytes(mi_blob + mi_off[fi], mi_len[fi]);
      c.put_u8(0);
      if (n_cigar) c.put_u32(uint32_t(n) << 4);  // one M run
      for (int64_t i = 0; i + 1 < n; i += 2)
        c.put_u8(uint8_t((kNt16[codes[i]] << 4) | kNt16[codes[i + 1]]));
      if (n & 1) c.put_u8(uint8_t(kNt16[codes[n - 1]] << 4));
      c.put_bytes(rqual, n);

      // tags, in the Python emitters' dict order:
      // MI cD cM cE cd ce [RX] [aD bD aM bM ad bd]
      c.put_bytes("MI", 2);
      c.put_u8('Z');
      c.put_bytes(mi_blob + mi_off[fi], mi_len[fi]);
      c.put_u8(0);
      const int16_t* drow = depth + row + lo0;
      const int16_t* erow = errors + row + lo0;
      int32_t dmax = 0, dmin = INT32_MAX;
      int64_t dtot = 0, etot = 0;
      for (int64_t i = 0; i < n; ++i) {
        const int32_t dv = drow[i];
        if (dv > dmax) dmax = dv;
        if (dv < dmin) dmin = dv;
        dtot += dv;
        etot += erow[i];
      }
      put_int_tag(c, "cD", dmax);
      put_int_tag(c, "cM", dmin);
      c.put_bytes("cE", 2);
      c.put_u8('f');
      c.put_f32(dtot ? float(double(etot) / double(dtot)) : 0.0f);
      put_arr_tag(c, "cd", drow, n, flip);
      put_arr_tag(c, "ce", erow, n, flip);
      if (bcount != nullptr) {
        // cB: 4 plane-major runs (A,C,G,T) of per-column raw DISSENT
        // counts (the call plane arrives zeroed —
        // models.molecular.sparsify_base_counts). Flipped records
        // complement the plane order (3-p) and reverse columns. The
        // subtype is 'C' (u8) when every count fits — half the bytes,
        // same decision as pipeline.calling._consensus_tags — else 'S'.
        uint16_t cbmax = 0;
        for (int plane = 0; plane < 4; ++plane) {
          const uint16_t* src =
              bcount + ((fi * 2 + role) * 4 + plane) * w + lo0;
          for (int64_t i = 0; i < n; ++i)
            if (src[i] > cbmax) cbmax = src[i];
        }
        const bool cb_u8 = cbmax < 256;
        c.put_bytes("cB", 2);
        c.put_u8('B');
        c.put_u8(cb_u8 ? 'C' : 'S');
        c.put_u32(uint32_t(4 * n));
        for (int plane = 0; plane < 4; ++plane) {
          const int src_plane = flip ? 3 - plane : plane;
          const uint16_t* src =
              bcount + ((fi * 2 + role) * 4 + src_plane) * w + lo0;
          for (int64_t i = 0; i < n; ++i) {
            const int64_t si = flip ? n - 1 - i : i;
            if (cb_u8) {
              c.put_u8(uint8_t(src[si]));
            } else {
              c.put_u16(src[si]);
            }
          }
        }
      }
      if (rx_len[fi] > 0) {
        c.put_bytes("RX", 2);
        c.put_u8('Z');
        c.put_bytes(rx_blob + rx_off[fi], rx_len[fi]);
        c.put_u8(0);
      }
      if (a_depth != nullptr) {
        const int16_t* arow = a_depth + row + lo0;
        const int16_t* brow = b_depth + row + lo0;
        int32_t amax = INT32_MIN, amin = INT32_MAX;
        int32_t bmax = INT32_MIN, bmin = INT32_MAX;
        for (int64_t i = 0; i < n; ++i) {
          const int32_t av = arow[i], bv = brow[i];
          if (av > amax) amax = av;
          if (av < amin) amin = av;
          if (bv > bmax) bmax = bv;
          if (bv < bmin) bmin = bv;
        }
        put_int_tag(c, "aD", amax);
        put_int_tag(c, "bD", bmax);
        put_int_tag(c, "aM", amin);
        put_int_tag(c, "bM", bmin);
        const bool emit_ss =
            a_ss_err != nullptr && b_ss_err != nullptr &&
            (ss_valid == nullptr || ss_valid[fi * 2 + role] != 0);
        if (emit_ss) {
          // aE/bE: strand error RATES vs the strand's own call (sum of
          // the ae/be arrays over the span / strand depth), mirroring
          // pipeline.calling._emit_duplex_batch
          const int16_t* aser = a_ss_err + row + lo0;
          const int16_t* bser = b_ss_err + row + lo0;
          int64_t atot = 0, btot = 0, asum = 0, bsum = 0;
          for (int64_t i = 0; i < n; ++i) {
            atot += arow[i];
            btot += brow[i];
            asum += aser[i];
            bsum += bser[i];
          }
          c.put_bytes("aE", 2);
          c.put_u8('f');
          c.put_f32(atot ? float(double(asum) / double(atot)) : 0.0f);
          c.put_bytes("bE", 2);
          c.put_u8('f');
          c.put_f32(btot ? float(double(bsum) / double(btot)) : 0.0f);
        }
        put_arr_tag(c, "ad", arow, n, flip);
        put_arr_tag(c, "bd", brow, n, flip);
        if (emit_ss) {
          put_arr_tag(c, "ae", a_ss_err + row + lo0, n, flip);
          put_arr_tag(c, "be", b_ss_err + row + lo0, n, flip);
        }
        if (a_call != nullptr && b_call != nullptr) {
          // ac/bc: per-strand consensus call strings (fgbio surface);
          // codes -> ACGTN, mirroring ops.encode.codes_to_seq —
          // reverse-complemented with the SEQ on flipped records
          static const char kBaseChar[6] = "ACGTN";
          for (int sc = 0; sc < 2; ++sc) {
            const int8_t* src = (sc ? b_call : a_call) + row + lo0;
            c.put_bytes(sc ? "bc" : "ac", 2);
            c.put_u8('Z');
            for (int64_t i = 0; i < n; ++i) {
              const int64_t si = flip ? n - 1 - i : i;
              uint8_t code = uint8_t(src[si]);
              if (code > 4) code = 4;
              if (flip) code = kComp[code];
              c.put_u8(uint8_t(kBaseChar[code]));
            }
            c.put_u8(0);
          }
        }
      }
      if (c.overflow) break;
      const int32_t block_size = int32_t(c.p - block_size_at - 4);
      std::memcpy(block_size_at, &block_size, 4);
      ++records;
    }
    if (c.overflow) break;
  }
  delete[] codes;
  delete[] rqual;
  if (c.overflow) return -1;
  *out_len = c.p - out;
  *n_records = records;
  *n_skipped = skipped;
  return 0;
}

namespace {

// One v2 b0 byte (models/duplex._duplex_b0):
//   base(3b) | a_depth<<3 | b_depth<<4 | a_err<<5 | b_err<<6
inline void decode_b0(uint8_t b0, int64_t i, int8_t* base, int16_t* depth,
                      int16_t* errors, int8_t* a_depth, int8_t* b_depth,
                      int8_t* a_err, int8_t* b_err) {
  const int8_t ad = int8_t((b0 >> 3) & 0x1);
  const int8_t bd = int8_t((b0 >> 4) & 0x1);
  const int8_t ae = int8_t((b0 >> 5) & 0x1);
  const int8_t be = int8_t((b0 >> 6) & 0x1);
  base[i] = int8_t(b0 & 0x7);
  depth[i] = int16_t(ad + bd);
  errors[i] = int16_t(ae + be);
  a_depth[i] = ad;
  b_depth[i] = bd;
  a_err[i] = ae;
  b_err[i] = be;
}

}  // namespace

// Unpack the family-major planar duplex output wire
// (models/duplex.pack_duplex_outputs, the NON-wire packed format): wire
// uint8 [f, 4, w] — per family, rows 0-1 = v2 b0 planes of duplex R1/R2,
// rows 2-3 = the consensus qual planes. Fills eight [f*2*w] arrays.
void wirepack_unpack_duplex_outputs(const uint8_t* wire, int64_t f, int64_t w,
                                    int8_t* base, uint8_t* qual,
                                    int16_t* depth, int16_t* errors,
                                    int8_t* a_depth, int8_t* b_depth,
                                    int8_t* a_err, int8_t* b_err) {
  for (int64_t fam = 0; fam < f; ++fam) {
    const uint8_t* plane_b = wire + fam * 4 * w;
    const uint8_t* plane_q = plane_b + 2 * w;
    const int64_t out0 = fam * 2 * w;
    for (int64_t i = 0; i < 2 * w; ++i) {
      decode_b0(plane_b[i], out0 + i, base, depth, errors, a_depth, b_depth,
                a_err, b_err);
      qual[out0 + i] = plane_q[i];
    }
  }
}

// Raw-unit conversion of the duplex kernel's presence planes
// (pipeline.calling._duplex_rawize, the C hot path): per family/role/
// strand, place the molecular cd/ce arrays into window space, mask by
// presence, fill synthetic boundary columns with the nearest raw value,
// and apply the strand-disagreement error rule. Inputs:
//   a_p/b_p/a_e/b_e int8 [f*2*w]  presence / error bits from the wire
//   row_pos int64 [f*4]  placement pos per (family, DUPLEX row); -1 absent
//   row_off int64 [f*4]  element offset into aux (cd at off, ce at off+len)
//   row_len int32 [f*4]
//   aux     u16 buffer, window_start int64 [f]
//   role_rows int32 [4] = (a_row role0, b_row role0, a_row role1, b_row r1)
// Outputs int16 [f*2*w]: ad, bd, ae, be, depth, errors. Families whose
// four row_pos are all -1 keep presence units (the caller passes the
// presence planes widened; this function only overwrites sidecar rows).
void wirepack_duplex_rawize(
    int64_t f, int64_t w, const int8_t* a_p, const int8_t* b_p,
    const int8_t* a_e, const int8_t* b_e, const int64_t* row_pos,
    const int64_t* row_off, const int32_t* row_len, const uint16_t* aux,
    const int64_t* window_start, const int32_t* role_rows, int16_t* ad,
    int16_t* bd, int16_t* ae, int16_t* be, int16_t* depth, int16_t* errors) {
  for (int64_t fi = 0; fi < f; ++fi) {
    for (int role = 0; role < 2; ++role) {
      const int64_t plane = (fi * 2 + role) * w;
      for (int strand = 0; strand < 2; ++strand) {
        const int row = role_rows[role * 2 + strand];
        const int8_t* pres = (strand == 0 ? a_p : b_p) + plane;
        const int8_t* errbit = (strand == 0 ? a_e : b_e) + plane;
        int16_t* draw = (strand == 0 ? ad : bd) + plane;
        int16_t* eraw = (strand == 0 ? ae : be) + plane;
        const int64_t k = fi * 4 + row;
        if (row_pos[k] < 0) continue;  // no sidecar: keep presence units
        const int64_t off = row_pos[k] - window_start[fi];
        const int32_t n = row_len[k];
        const uint16_t* cd = aux + row_off[k];
        const uint16_t* ce = cd + n;
        const int64_t lo = off < 0 ? 0 : off;
        int64_t hi = off + n;
        if (hi > w) hi = w;
        // nearest in-range source column for the boundary fill
        const int64_t lo_src = lo - off, hi_src = hi - 1 - off;
        for (int64_t i = 0; i < w; ++i) {
          if (!pres[i]) {
            draw[i] = 0;
            eraw[i] = 0;
            continue;
          }
          int64_t s = i - off;
          if (s < lo_src) s = lo_src;
          if (s > hi_src) s = hi_src;
          int32_t d = 0, e = 0;
          if (hi > lo && s >= 0 && s < n) {
            d = cd[s];
            e = ce[s];
            // exact only at the record's own columns; boundary columns
            // (conversion prepend / extend copies) borrow the nearest
            int64_t own = i - off;
            if (own >= 0 && own < n && cd[own] != 0) {
              d = cd[own];
              e = ce[own];
            }
          }
          if (errbit[i]) e = d - e;  // strand disagrees with the call
          if (e < 0) e = 0;
          draw[i] = int16_t(d);
          eraw[i] = int16_t(e);
        }
      }
      // totals
      int16_t* drow = depth + plane;
      int16_t* erow = errors + plane;
      const int16_t* arow = ad + plane;
      const int16_t* brow = bd + plane;
      const int16_t* aer = ae + plane;
      const int16_t* ber = be + plane;
      for (int64_t i = 0; i < w; ++i) {
        drow[i] = int16_t(arow[i] + brow[i]);
        erow[i] = int16_t(aer[i] + ber[i]);
      }
    }
  }
}

// One-pass duplex retire for the b0-only tunnel wire: decode the b0
// planes AND reconstruct the consensus qual plane from the kernel-built
// tables over the host's own evolved input quals
// (ops/reconstruct.py is the numpy reference; this is the hot path —
// the numpy retire was the largest serial block of the on-chip stage).
//
//   b0_planes u8 [f, 2, w]   the D2H wire (base|a_p|b_p|a_e|b_e bits)
//   cover     u8 [f, 4, w]   pre-transform row coverage (host's own)
//   quals_pre f32 [f, 4, w]  pre-transform observation quals
//   la/rd     i8 [f, 4], eligible u8 [f]  (la/rd ride the wire)
//   role_rows i32 [4]        (a_row, b_row) per role
//   t_single u8 [256], t_agree/t_dis u8 [256*256]  (qa-major)
// Outputs [f, 2, w]: base i8, qual u8, depth/errors i16, a/b presence
// and error bits i8.
void wirepack_duplex_retire(
    const uint8_t* b0_planes, int64_t f, int64_t w, const uint8_t* cover,
    const float* quals_pre, const int8_t* la, const int8_t* rd,
    const uint8_t* eligible, const int32_t* role_rows,
    const uint8_t* t_single, const uint8_t* t_agree, const uint8_t* t_dis,
    int8_t* base, uint8_t* qual, int16_t* depth, int16_t* errors,
    int8_t* a_p_out, int8_t* b_p_out, int8_t* a_e_out, int8_t* b_e_out) {
  constexpr uint8_t kPrependQual = 40;  // ops/convert.py PREPEND_QUAL
  constexpr uint8_t kNoCall = 2;        // ops/phred.py NO_CALL_QUAL
  constexpr int8_t kNBase = 4;
  std::vector<uint8_t> q(4 * size_t(w));
  std::vector<uint8_t> cov(4 * size_t(w));
  for (int64_t fi = 0; fi < f; ++fi) {
    // ---- evolve quals/cover (numpy twin: ops/reconstruct.py) ----
    for (int row = 0; row < 4; ++row) {
      const float* src = quals_pre + (fi * 4 + row) * w;
      const uint8_t* cv = cover + (fi * 4 + row) * w;
      uint8_t* qd = q.data() + row * w;
      uint8_t* cd = cov.data() + row * w;
      for (int64_t i = 0; i < w; ++i) {
        qd[i] = uint8_t(src[i]);
        cd[i] = cv[i];
      }
    }
    int64_t first[4], last[4];
    bool has[4];
    auto span_of = [&](int row) {
      const uint8_t* cd = cov.data() + row * w;
      int64_t lo = -1, hi = -1;
      for (int64_t i = 0; i < w; ++i)
        if (cd[i]) {
          if (lo < 0) lo = i;
          hi = i;
        }
      first[row] = lo < 0 ? 0 : lo;
      last[row] = hi < 0 ? 0 : hi;
      has[row] = lo >= 0;
    };
    for (int row = 0; row < 4; ++row) {
      span_of(row);
      // conversion prepend (la==1 implies first>0 by construction)
      if (la[fi * 4 + row] == 1 && has[row] && first[row] > 0) {
        q[row * w + first[row] - 1] = kPrependQual;
        cov[row * w + first[row] - 1] = 1;
      }
      // trailing trim (prepend only changes the left edge)
      if (rd[fi * 4 + row] == 1 && has[row]) cov[row * w + last[row]] = 0;
    }
    // post-convert state for the extend copies
    for (int row = 0; row < 4; ++row) span_of(row);
    const bool elig = eligible[fi] != 0;
    const int pairs[2][2] = {{1, 0}, {2, 3}};
    for (const auto& pr : pairs) {
      const int left = pr[0], right = pr[1];
      const bool both = has[left] && has[right] && elig;
      if (both && la[fi * 4 + left] == 1) {
        const int64_t c = first[left];
        q[right * w + c] = q[left * w + c];
        cov[right * w + c] = 1;
      }
      if (both && rd[fi * 4 + left] == 1) {
        const int64_t c = last[right];
        q[left * w + c] = q[right * w + c];
        cov[left * w + c] = 1;
      }
    }
    // ---- decode b0 + qual lookup per role/column ----
    for (int role = 0; role < 2; ++role) {
      const uint8_t* b0 = b0_planes + (fi * 2 + role) * w;
      const int64_t out0 = (fi * 2 + role) * w;
      const uint8_t* qa_row = q.data() + role_rows[role * 2] * w;
      const uint8_t* qb_row = q.data() + role_rows[role * 2 + 1] * w;
      for (int64_t i = 0; i < w; ++i) {
        decode_b0(b0[i], out0 + i, base, depth, errors, a_p_out, b_p_out,
                  a_e_out, b_e_out);
        const int8_t ap = a_p_out[out0 + i];
        const int8_t bp = b_p_out[out0 + i];
        const int8_t ae = a_e_out[out0 + i];
        const int8_t be = b_e_out[out0 + i];
        const int8_t bs = base[out0 + i];
        uint8_t qv = kNoCall;
        const bool masked = bs == kNBase;
        if (ap && bp) {
          if (ae || be)
            qv = t_dis[size_t(qa_row[i]) * 256 + qb_row[i]];
          else if (!masked)
            qv = t_agree[size_t(qa_row[i]) * 256 + qb_row[i]];
        } else if (ap && !masked) {
          qv = t_single[qa_row[i]];
        } else if (bp && !masked) {
          qv = t_single[qb_row[i]];
        }
        qual[out0 + i] = qv;
      }
    }
  }
}

// Unpack the b0-only tunnel wire (models/duplex.pack_duplex_b0_outputs):
// wire uint8 [f, 2, w] b0 planes, no qual (reconstructed host-side by
// ops.reconstruct). Fills seven [f*2*w] arrays.
void wirepack_unpack_duplex_b0(const uint8_t* wire, int64_t f, int64_t w,
                               int8_t* base, int16_t* depth, int16_t* errors,
                               int8_t* a_depth, int8_t* b_depth,
                               int8_t* a_err, int8_t* b_err) {
  const int64_t n = f * 2 * w;
  for (int64_t i = 0; i < n; ++i)
    decode_b0(wire[i], i, base, depth, errors, a_depth, b_depth, a_err, b_err);
}

// ---- native raw-blob record sort (pipeline/extsort.py 'native' engine) ----
//
// One in-RAM spill run: a concatenated stream of encoded BAM records
// (each with its leading block_size prefix — the native emit /
// BamReader.raw_records framing) is key-scanned at fixed offsets,
// stable-sorted, and gathered into `out` in sorted order. The key is
// EXACTLY pipeline.extsort.raw_coordinate_key's tuple — (ref_id or
// 1<<30, pos or 1<<30, qname bytes, flag), compared like Python compares
// it (lexicographic bytes with shorter-prefix-first, unsigned flag) —
// and std::stable_sort preserves input order on full ties like
// list.sort, so for any run partitioning into contiguous input chunks
// the merged output is byte-identical to the Python engine's.
//
// key_s / sort_s return the pass split (key extraction vs order+gather)
// so the bench's sort_write sub-attribution comes from measurement.
// Returns record count, or -2 on a malformed record frame (a corrupt
// block_size / overrun — these blobs are internally produced, so this
// is a bug or memory corruption, never input data).

namespace {

struct RawRecKey {
  int64_t off;        // byte offset of the record (incl. prefix)
  int32_t size;       // total bytes incl. prefix
  int32_t ref, pos;   // already mapped (-1 -> 1<<30)
  int32_t qlen;
  uint16_t flag;
};

constexpr int32_t kMinRecordSize = 32;        // io/bam.py MIN_RECORD_SIZE
constexpr int32_t kMaxRecordSize = 1 << 28;   // io/bam.py MAX_RECORD_SIZE
constexpr int32_t kUnmappedKey = 1 << 30;     // raw_coordinate_key sentinel

inline bool scan_raw_key(const uint8_t* blob, int64_t nbytes, int64_t off,
                         RawRecKey& k) {
  if (off + 4 > nbytes) return false;
  int32_t bs;
  std::memcpy(&bs, blob + off, 4);
  if (bs < kMinRecordSize || bs > kMaxRecordSize || off + 4 + bs > nbytes)
    return false;
  k.off = off;
  k.size = bs + 4;
  int32_t ref, pos;
  std::memcpy(&ref, blob + off + 4, 4);
  std::memcpy(&pos, blob + off + 8, 4);
  k.ref = ref >= 0 ? ref : kUnmappedKey;
  k.pos = pos >= 0 ? pos : kUnmappedKey;
  std::memcpy(&k.flag, blob + off + 18, 2);
  const int32_t lq = blob[off + 12];
  k.qlen = lq > 0 ? lq - 1 : 0;
  if (36 + k.qlen > k.size) return false;
  return true;
}

// raw_coordinate_key tuple comparison (qname bytes compare like Python
// bytes: memcmp, then shorter-is-smaller).
inline bool raw_key_less(const uint8_t* blob, const RawRecKey& a,
                         const RawRecKey& b) {
  if (a.ref != b.ref) return a.ref < b.ref;
  if (a.pos != b.pos) return a.pos < b.pos;
  const int n = a.qlen < b.qlen ? a.qlen : b.qlen;
  const int c = std::memcmp(blob + a.off + 36, blob + b.off + 36, size_t(n));
  if (c != 0) return c < 0;
  if (a.qlen != b.qlen) return a.qlen < b.qlen;
  return a.flag < b.flag;
}

}  // namespace

int64_t wirepack_sort_raw_records(const uint8_t* blob, int64_t nbytes,
                                  uint8_t* out, double* key_s,
                                  double* sort_s) {
  using clock = std::chrono::steady_clock;
  const auto t0 = clock::now();
  std::vector<RawRecKey> keys;
  keys.reserve(size_t(nbytes / 256) + 16);
  int64_t off = 0;
  while (off < nbytes) {
    RawRecKey k;
    if (!scan_raw_key(blob, nbytes, off, k)) return -2;
    keys.push_back(k);
    off += k.size;
  }
  const auto t1 = clock::now();
  std::stable_sort(keys.begin(), keys.end(),
                   [blob](const RawRecKey& a, const RawRecKey& b) {
                     return raw_key_less(blob, a, b);
                   });
  uint8_t* dst = out;
  for (const RawRecKey& k : keys) {
    std::memcpy(dst, blob + k.off, size_t(k.size));
    dst += k.size;
  }
  const auto t2 = clock::now();
  if (key_s)
    *key_s = std::chrono::duration<double>(t1 - t0).count();
  if (sort_s)
    *sort_s = std::chrono::duration<double>(t2 - t1).count();
  return int64_t(keys.size());
}

// ---- coordinate-bucketed emit sweeps (pipeline/bucketemit.py) ------------
//
// The bucket router's native pass beside the raw sort: one frame scan
// assigns every record in a concatenated blob to a contig/position-range
// bucket, one scatter concatenates the records per bucket in input
// order. The bucket key is the (ref, pos) PREFIX of raw_coordinate_key
// folded into one int64 — ref * 2^31 + pos with the same -1 -> 1<<30
// mapping — so a bucket boundary can never split a full-key tie (qname/
// flag only break ties at one (ref, pos)) and the concatenation of
// per-bucket stable sorts in plan order is byte-identical to the global
// stable sort.
//
// wirepack_bucket_assign: boundaries int64 ascending, boundaries[0]==0
// (bucket i covers [bounds[i], bounds[i+1]), the last to +inf — which
// includes the unmapped sentinel key). Writes per-record off/size/bucket
// into caller arrays of capacity `cap` (nbytes/36 bounds the record
// count: min frame is 4 + kMinRecordSize). Returns the record count,
// -2 on a malformed frame, -3 if cap is exceeded.
int64_t wirepack_bucket_assign(const uint8_t* blob, int64_t nbytes,
                               const int64_t* bounds, int32_t nbounds,
                               int64_t cap, int64_t* offs, int32_t* sizes,
                               int32_t* buckets) {
  int64_t n = 0;
  int64_t off = 0;
  while (off < nbytes) {
    RawRecKey k;
    if (!scan_raw_key(blob, nbytes, off, k)) return -2;
    if (n >= cap) return -3;
    const int64_t key = int64_t(k.ref) * (int64_t(1) << 31) + k.pos;
    // upper_bound - 1: the rightmost boundary <= key
    int32_t lo = 0, hi = nbounds;
    while (lo < hi) {
      const int32_t mid = (lo + hi) / 2;
      if (bounds[mid] <= key)
        lo = mid + 1;
      else
        hi = mid;
    }
    offs[n] = off;
    sizes[n] = k.size;
    buckets[n] = lo - 1;
    ++n;
    off += k.size;
  }
  return n;
}

// wirepack_bucket_scatter: copy n records (assign's off/size/bucket
// arrays) into `out` — records of bucket b land contiguously starting
// at starts[b] (caller-computed exclusive prefix sums of per-bucket
// byte totals), preserving input order within each bucket. Returns 0,
// or -2 if any record would overrun starts[b+1] (a stale plan — the
// caller's totals must come from the same assign pass).
int64_t wirepack_bucket_scatter(const uint8_t* blob, int64_t n,
                                const int64_t* offs, const int32_t* sizes,
                                const int32_t* buckets, int32_t nbuckets,
                                const int64_t* starts, int64_t out_bytes,
                                uint8_t* out) {
  std::vector<int64_t> cursor(starts, starts + nbuckets);
  for (int64_t i = 0; i < n; ++i) {
    const int32_t b = buckets[i];
    const int64_t end =
        b + 1 < nbuckets ? starts[b + 1] : out_bytes;
    if (b < 0 || b >= nbuckets || cursor[b] + sizes[i] > end) return -2;
    std::memcpy(out + cursor[b], blob + offs[i], size_t(sizes[i]));
    cursor[b] += sizes[i];
  }
  return 0;
}

// ---- sparse cB dissent histogram (models/molecular.py twin) --------------
//
// The molecular emit path's tag prologue: overlap co-call
// (_overlap_cocall_np), observation filter, per-base histogram
// (_base_histogram), and call-plane sparsification
// (sparsify_base_counts) — four numpy sweeps over [F, T, 2, W] — as ONE
// C pass. Integer-exact twin of the numpy chain (every operation is a
// comparison, sum, or absolute difference of integers; tests pin
// equality). The r05 ledger's molecular-emit wall was largely this
// rework running inside the emit span per batch.
//
//   bases i8 [f, t, 2, w], quals u8 [f, t, 2, w] (<= 93+93 co-called),
//   cons  i8 [f, 2, w]  (the consensus call plane; NBASE = masked),
//   min_q: observation threshold (post-cocall), cocall: 1 = co-call on.
//   out  u16 [f, 2, 4, w], fully written (zeros included).
void wirepack_bcount_sparse(const int8_t* bases, const uint8_t* quals,
                            int64_t f, int64_t t, int64_t w,
                            const int8_t* cons, int min_q, int cocall,
                            uint16_t* out) {
  constexpr int8_t kN = 4;
  for (int64_t fi = 0; fi < f; ++fi) {
    uint16_t* ob = out + fi * 2 * 4 * w;
    std::memset(ob, 0, sizeof(uint16_t) * 2 * 4 * size_t(w));
    for (int64_t ti = 0; ti < t; ++ti) {
      const int8_t* b1 = bases + ((fi * t + ti) * 2 + 0) * w;
      const int8_t* b2 = b1 + w;
      const uint8_t* q1 = quals + ((fi * t + ti) * 2 + 0) * w;
      const uint8_t* q2 = q1 + w;
      for (int64_t i = 0; i < w; ++i) {
        int8_t x1 = b1[i], x2 = b2[i];
        int q1v = q1[i], q2v = q2[i];
        if (cocall) {
          const bool both = x1 != kN && x2 != kN;
          if (both) {
            if (x1 == x2) {
              const int qs = q1v + q2v;
              q1v = qs;
              q2v = qs;
            } else {
              const int qd = q1v >= q2v ? q1v - q2v : q2v - q1v;
              if (qd == 0) {  // tie masks the column on both rows
                x1 = kN;
                x2 = kN;
              } else {
                const int8_t win = q1v >= q2v ? x1 : x2;
                x1 = win;
                x2 = win;
              }
              q1v = qd;
              q2v = qd;
            }
          }
        }
        if (x1 != kN && q1v >= min_q) ob[size_t(x1) * w + i]++;
        if (x2 != kN && q2v >= min_q) ob[(4 + size_t(x2)) * w + i]++;
      }
    }
    // sparsify: zero the consensus-call plane wherever the call exists
    for (int role = 0; role < 2; ++role) {
      const int8_t* crow = cons + (fi * 2 + role) * w;
      uint16_t* orole = ob + size_t(role) * 4 * w;
      for (int64_t i = 0; i < w; ++i) {
        const int8_t c = crow[i];
        if (c != kN) orole[size_t(c) * w + i] = 0;
      }
    }
  }
}

// ---- native strand-call planes (ops/hosttwin.py strand_call_planes) ----
//
// The duplex rawize pass's largest numpy segment: the host twin of the
// convert -> extend window transforms, recomputed per retired batch to
// recover the per-strand consensus calls (ac/bc tags, exact-ce input).
// This is the C sweep of the same integer rules, term for term:
// ops.hosttwin.convert_np (prepend, per-column rewrite, trailing trim)
// then extend_np (boundary-column copies between pair rows, PAIRS =
// ((1,0),(2,3))), then the coverage mask. The numpy twin stays as the
// parity reference (tests/test_hosttwin.py pins it against the jit ops;
// tests/test_wirepack.py pins this against the numpy twin).
//
//   bases int8 [f, 4, w], cover u8 [f, 4, w], ref int8 [f, w+1],
//   cmask u8 [f, 4], elig u8 [f]  ->  calls int8 [f, 4, w]
//   (NBASE where the transformed row has no coverage).
void wirepack_strand_calls(const int8_t* bases, const uint8_t* cover,
                           const int8_t* ref, const uint8_t* cmask,
                           const uint8_t* elig, int64_t f, int64_t w,
                           int8_t* calls) {
  constexpr int8_t kA = 0, kC = 1, kG = 2, kT = 3, kN = 4;
  std::vector<int8_t> b(4 * size_t(w));
  std::vector<uint8_t> c(4 * size_t(w));
  for (int64_t fam = 0; fam < f; ++fam) {
    std::memcpy(b.data(), bases + fam * 4 * w, 4 * size_t(w));
    std::memcpy(c.data(), cover + fam * 4 * w, 4 * size_t(w));
    const int8_t* refrow = ref + fam * (w + 1);
    int8_t la[4] = {0, 0, 0, 0}, rd[4] = {0, 0, 0, 0};
    for (int row = 0; row < 4; ++row) {
      int8_t* br = b.data() + row * w;
      uint8_t* cr = c.data() + row * w;
      int64_t first = -1;
      for (int64_t i = 0; i < w; ++i)
        if (cr[i]) {
          first = i;
          break;
        }
      const bool act = cmask[fam * 4 + row] != 0 && first >= 0;
      if (!act) continue;
      // conversion prepend: one column left of the read, ref base there
      if (first > 0) {
        br[first - 1] = refrow[first - 1];
        cr[first - 1] = 1;
        la[row] = 1;
      }
      // per-column rewrite, left to right in place: reading br[i + 1]
      // before it is rewritten matches the numpy twin's vectorized
      // select over the post-prepend (pre-rewrite) values
      for (int64_t i = 0; i < w; ++i) {
        if (!cr[i]) continue;
        const int8_t x = br[i];
        const int8_t refc = refrow[i], refn = refrow[i + 1];
        if (x == kA && refc == kG) {
          br[i] = kG;
        } else if (x == kC) {
          if (refc == kC && refn == kG) {  // CpG: pair rule
            const int8_t nxt = i + 1 < w ? br[i + 1] : kN;
            const bool nxtcov = i + 1 < w && cr[i + 1] != 0;
            if (nxtcov && nxt == kA) br[i] = kT;
          } else {
            br[i] = kT;
          }
        }
      }
      // trailing trim: ref past the end is G and the row now ends in C
      int64_t last = -1;
      for (int64_t i = w - 1; i >= 0; --i)
        if (cr[i]) {
          last = i;
          break;
        }
      if (last >= 0 && refrow[last + 1] == kG && br[last] == kC) {
        cr[last] = 0;
        br[last] = kN;
        rd[row] = 1;
      }
    }
    // extend-gap boundary copies (ops/extend.PAIRS, left = converted row)
    const int pairs[2][2] = {{1, 0}, {2, 3}};
    for (const auto& pr : pairs) {
      const int left = pr[0], right = pr[1];
      int8_t* bl = b.data() + left * w;
      int8_t* brr = b.data() + right * w;
      uint8_t* cl = c.data() + left * w;
      uint8_t* crr = c.data() + right * w;
      bool has_l = false, has_r = false;
      int64_t first_l = 0, last_r = 0;
      for (int64_t i = 0; i < w; ++i)
        if (cl[i]) {
          first_l = i;
          has_l = true;
          break;
        }
      for (int64_t i = w - 1; i >= 0; --i)
        if (crr[i]) {
          last_r = i;
          has_r = true;
          break;
        }
      const bool both = has_l && has_r && elig[fam] != 0;
      if (both && la[left] == 1) {
        brr[first_l] = bl[first_l];
        crr[first_l] = 1;
      }
      if (both && rd[left] == 1) {
        bl[last_r] = brr[last_r];
        cl[last_r] = 1;
      }
    }
    int8_t* dst = calls + fam * 4 * w;
    for (int64_t i = 0; i < 4 * w; ++i) dst[i] = c[i] ? b[i] : kN;
  }
}


// Methylation tally merge (methyl/tally.py twin): reduce n (site, ctx,
// meth, unmeth) tuples — duplicated sites allowed — to sorted unique rows
// with summed counts. ctx is a pure function of the site (genome context),
// so the first occurrence's value is THE value. Returns m (unique rows);
// out arrays are caller-allocated with capacity n. Stable index sort, so
// ties keep input order exactly like numpy argsort(kind="stable").
int64_t wirepack_methyl_tally_merge(
    const int64_t* sites, const uint8_t* ctx, const uint32_t* meth,
    const uint32_t* unmeth, int64_t n, int64_t* out_sites,
    uint8_t* out_ctx, uint32_t* out_meth, uint32_t* out_unmeth) {
  std::vector<int64_t> order(static_cast<size_t>(n));
  for (int64_t i = 0; i < n; ++i) order[static_cast<size_t>(i)] = i;
  std::stable_sort(order.begin(), order.end(),
                   [sites](int64_t a, int64_t b) {
                     return sites[a] < sites[b];
                   });
  int64_t m = 0;
  for (int64_t k = 0; k < n; ++k) {
    const int64_t i = order[static_cast<size_t>(k)];
    if (m > 0 && out_sites[m - 1] == sites[i]) {
      out_meth[m - 1] += meth[i];
      out_unmeth[m - 1] += unmeth[i];
    } else {
      out_sites[m] = sites[i];
      out_ctx[m] = ctx[i];
      out_meth[m] = meth[i];
      out_unmeth[m] = unmeth[i];
      ++m;
    }
  }
  return m;
}

}  // extern "C"
