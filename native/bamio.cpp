// Native BGZF/BAM codec for bsseqconsensusreads_tpu.
//
// The reference delegates its hot record I/O to C (htslib via pysam and
// samtools; SURVEY.md §2.2). This is the framework's equivalent: a zlib-based
// BGZF stream codec plus a columnar record parser that converts the BAM
// alignment stream straight into flat arrays (positions, flags, base codes,
// quals, cigars, MI/RX tags) so the Python layer never touches per-record
// objects on the hot path. Exposed as a plain C ABI for ctypes
// (bsseqconsensusreads_tpu/io/native.py); the pure-Python codec remains the
// fallback.
//
// Build: make -C native   (produces libbamio.so)

#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <condition_variable>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <thread>
#include <vector>
#include <zlib.h>

namespace {

constexpr size_t kMaxBlock = 65536;

// FNV-1a 64-bit over raw bytes — the one byte-loop hash in this file,
// shared by the grouper's `flushed` reappearance set and the encode
// scan's qname/RX tables. The flushed set exists ONLY for the
// refragmented diagnostic counter, but it must remember every family
// ever closed: as std::string entries it would grow to ~3 GB over a
// 100M-read run (38M keys x ~80 B of node+SSO+malloc); 8-byte hashes
// cut that ~4x, and a collision (p ~ 4e-5 at 38M keys) can only nudge
// a counter, never the grouping.
inline uint64_t fnv1a64(const uint8_t* p, size_t n) {
  uint64_t h = 14695981039346656037ull;
  for (size_t i = 0; i < n; i++) {
    h ^= p[i];
    h *= 1099511628211ull;
  }
  return h;
}

inline uint64_t fnv1a64(const std::string& s) {
  return fnv1a64(reinterpret_cast<const uint8_t*>(s.data()), s.size());
}

struct MtInflate;

struct Reader {
  FILE* fh = nullptr;
  std::vector<uint8_t> carry;  // decompressed bytes not yet consumed
  size_t carry_off = 0;
  std::vector<uint8_t> pending;  // parsed-but-unreturned record body
  bool last_block_empty = false;
  bool eof = false;
  std::string err;
  MtInflate* mt = nullptr;  // parallel-inflate pipeline (bamio_open_mt)
};

struct Writer {
  FILE* fh = nullptr;
  std::vector<uint8_t> buf;
  int level = 6;
  std::string err;
};

bool compress_block(const uint8_t* data, size_t n, int level,
                    std::vector<uint8_t>& out, std::string& err);

// Shared BGZF payload chunking: fill `buf` to exactly 65280 bytes, then
// hand off via flush() (which must leave buf ready for refill). One source
// of truth for the block-boundary invariant both writers' byte-identical
// guarantee rests on.
template <typename FlushFn>
int buffered_write(std::vector<uint8_t>& buf, const uint8_t* data, int64_t n,
                   FlushFn flush) {
  int64_t off = 0;
  while (off < n) {
    size_t room = 65280 - buf.size();
    size_t take = size_t(n - off) < room ? size_t(n - off) : room;
    buf.insert(buf.end(), data + off, data + off + take);
    off += take;
    if (buf.size() == 65280) {
      if (!flush()) return -1;
    }
  }
  return 0;
}

// ---- multi-threaded BGZF writer ----
//
// BGZF parallelizes trivially: each 64 KB block compresses independently
// and the file is their in-order concatenation, so a worker pool behind
// the same 65280-byte chunking produces BYTE-IDENTICAL output to the
// single-threaded writer (tests/test_native.py asserts it). The submitting
// thread drains completed jobs from the queue front in submission order;
// a bounded queue applies backpressure so memory stays O(threads) blocks.

struct MtJob {
  std::vector<uint8_t> raw;    // uncompressed payload
  std::vector<uint8_t> block;  // finished on-disk block
  bool claimed = false;
  bool done = false;
  bool failed = false;
  std::string err;
};

struct MtWriter {
  FILE* fh = nullptr;
  int level = 6;
  std::string err;
  std::vector<uint8_t> buf;
  std::deque<std::unique_ptr<MtJob>> queue;  // submission order
  std::mutex mu;
  std::condition_variable cv_work;  // workers wait: unclaimed job / stop
  std::condition_variable cv_done;  // submitter waits: front done / room
  std::vector<std::thread> workers;
  bool stop = false;
  size_t max_queue = 16;

  ~MtWriter() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stop = true;
    }
    cv_work.notify_all();
    for (auto& t : workers) t.join();
  }
};

void mt_worker(MtWriter* w) {
  for (;;) {
    MtJob* job = nullptr;
    {
      std::unique_lock<std::mutex> lk(w->mu);
      w->cv_work.wait(lk, [&] {
        if (w->stop) return true;
        for (auto& j : w->queue)
          if (!j->claimed) return true;
        return false;
      });
      if (w->stop) return;
      for (auto& j : w->queue)
        if (!j->claimed) {
          j->claimed = true;
          job = j.get();
          break;
        }
    }
    if (!job) continue;
    std::string err;
    const bool ok =
        compress_block(job->raw.data(), job->raw.size(), w->level, job->block, err);
    {
      std::lock_guard<std::mutex> lk(w->mu);
      job->done = true;
      job->failed = !ok;
      job->err = err;
    }
    w->cv_done.notify_all();
  }
}

// Write out every completed job at the queue front; when `all`, wait for
// the whole queue to drain. Returns false (setting w->err) on any failure.
bool mt_drain(MtWriter* w, bool all) {
  std::unique_lock<std::mutex> lk(w->mu);
  for (;;) {
    while (!w->queue.empty() && w->queue.front()->done) {
      std::unique_ptr<MtJob> job = std::move(w->queue.front());
      w->queue.pop_front();
      if (job->failed) {
        w->err = job->err;
        return false;
      }
      lk.unlock();  // fwrite outside the lock: workers keep compressing
      const bool ok =
          fwrite(job->block.data(), 1, job->block.size(), w->fh) ==
          job->block.size();
      lk.lock();
      if (!ok) {
        w->err = "write failed";
        return false;
      }
    }
    const bool blocked =
        all ? !w->queue.empty()
            : (w->queue.size() >= w->max_queue && !w->queue.front()->done);
    if (!blocked) return true;
    w->cv_done.wait(lk, [&] {
      return !w->queue.empty() && w->queue.front()->done;
    });
  }
}

bool mt_submit(MtWriter* w, std::vector<uint8_t>&& payload) {
  if (!mt_drain(w, false)) return false;  // backpressure + in-order writes
  {
    std::lock_guard<std::mutex> lk(w->mu);
    auto job = std::make_unique<MtJob>();
    job->raw = std::move(payload);
    w->queue.push_back(std::move(job));
  }
  w->cv_work.notify_one();
  return true;
}

const uint8_t kEofBlock[28] = {0x1f, 0x8b, 0x08, 0x04, 0,    0,    0,    0,
                               0,    0xff, 0x06, 0x00, 0x42, 0x43, 0x02, 0x00,
                               0x1b, 0x00, 0x03, 0x00, 0,    0,    0,    0,
                               0,    0,    0,    0};

// nt16 code -> framework base code (A=0 C=1 G=2 T=3 N/other=4)
const int8_t kNt16ToCode[16] = {4, 0, 1, 4, 2, 4, 4, 4, 3, 4, 4, 4, 4, 4, 4, 4};

// One on-disk BGZF block, fetched but not yet inflated.
struct RawBlock {
  std::vector<uint8_t> cdata;
  uint32_t crc = 0;
  uint32_t isize = 0;
};

// Read the next block's compressed payload from the stream. Sequential —
// one caller at a time owns the FILE*. `last_empty` is the EOF-marker
// state (BGZF ends with an empty block): carried across calls, validated
// when fread hits EOF. Returns 1 = block fetched, 0 = clean EOF,
// -1 = error (err set).
int fetch_raw_block(FILE* fh, RawBlock& b, bool& last_empty,
                    std::string& err) {
  uint8_t head[12];
  size_t got = fread(head, 1, 12, fh);
  if (got == 0) {
    if (!last_empty) {
      err = "BGZF EOF marker missing (file truncated?)";
      return -1;
    }
    return 0;
  }
  if (got < 12 || head[0] != 0x1f || head[1] != 0x8b || head[2] != 8 ||
      !(head[3] & 4)) {
    err = "not a BGZF stream";
    return -1;
  }
  uint16_t xlen = uint16_t(head[10]) | (uint16_t(head[11]) << 8);
  std::vector<uint8_t> extra(xlen);
  if (fread(extra.data(), 1, xlen, fh) != xlen) {
    err = "truncated BGZF extra field";
    return -1;
  }
  int bsize = -1;
  for (size_t off = 0; off + 4 <= extra.size();) {
    uint8_t si1 = extra[off], si2 = extra[off + 1];
    uint16_t slen = uint16_t(extra[off + 2]) | (uint16_t(extra[off + 3]) << 8);
    if (si1 == 0x42 && si2 == 0x43 && slen == 2) {
      bsize = (int(extra[off + 4]) | (int(extra[off + 5]) << 8)) + 1;
      break;
    }
    off += 4 + slen;
  }
  if (bsize < 0) {
    err = "BGZF block missing BC subfield";
    return -1;
  }
  long cdata_len = long(bsize) - 12 - xlen - 8;
  if (cdata_len < 0) {
    err = "corrupt BGZF BSIZE";
    return -1;
  }
  b.cdata.resize(cdata_len);
  uint8_t tail[8];
  if (fread(b.cdata.data(), 1, cdata_len, fh) != size_t(cdata_len) ||
      fread(tail, 1, 8, fh) != 8) {
    err = "truncated BGZF block";
    return -1;
  }
  b.crc = uint32_t(tail[0]) | (uint32_t(tail[1]) << 8) |
          (uint32_t(tail[2]) << 16) | (uint32_t(tail[3]) << 24);
  b.isize = uint32_t(tail[4]) | (uint32_t(tail[5]) << 8) |
            (uint32_t(tail[6]) << 16) | (uint32_t(tail[7]) << 24);
  if (b.isize > kMaxBlock) {
    // untrusted 32-bit field: bounding it here keeps a corrupt block from
    // driving huge allocations (fatal in a worker thread, where bad_alloc
    // would escape to std::terminate instead of an IOError)
    err = "corrupt BGZF ISIZE";
    return -1;
  }
  last_empty = (b.isize == 0);
  return 1;
}

// Inflate + CRC-check one fetched block into out[b.isize]. Pure function
// of the block — safe from any thread.
bool inflate_block(const RawBlock& b, uint8_t* out, std::string& err) {
  z_stream zs;
  memset(&zs, 0, sizeof(zs));
  if (inflateInit2(&zs, -15) != Z_OK) {
    err = "inflateInit failed";
    return false;
  }
  zs.next_in = const_cast<uint8_t*>(b.cdata.data());
  zs.avail_in = uInt(b.cdata.size());
  zs.next_out = out;
  zs.avail_out = b.isize;
  int rc = inflate(&zs, Z_FINISH);
  inflateEnd(&zs);
  if (rc != Z_STREAM_END || zs.total_out != b.isize) {
    err = "BGZF inflate failed / ISIZE mismatch";
    return false;
  }
  if (crc32(0L, out, b.isize) != b.crc) {
    err = "BGZF CRC mismatch";
    return false;
  }
  return true;
}

// --- multi-threaded inflate pipeline (the read-side twin of MtWriter) ----
// The consumer thread fetches compressed blocks sequentially (cheap — page
// cache memcpys) into a bounded in-order queue; workers inflate+CRC them
// concurrently; delivery pops strictly in fetch order, so the decompressed
// stream is byte-identical to the single-threaded path.

struct InflJob {
  RawBlock raw;
  std::vector<uint8_t> out;
  bool done = false;
  std::string err;  // non-empty = this block failed
};

struct MtInflate {
  std::mutex mu;
  std::condition_variable cv_work;  // workers: todo became non-empty / stop
  std::condition_variable cv_done;  // consumer: a job completed
  std::deque<std::shared_ptr<InflJob>> order;  // delivery order, in flight
  std::deque<std::shared_ptr<InflJob>> todo;   // not yet taken by a worker
  std::vector<std::thread> workers;
  bool stop = false;
  bool fetch_eof = false;     // no more blocks will be fetched
  std::string fetch_err;      // terminal fetch error (delivered last)
  size_t window = 32;         // max blocks in flight (~4 MB ceiling)
};

void mt_inflate_worker(MtInflate* m) {
  std::unique_lock<std::mutex> lk(m->mu);
  while (true) {
    m->cv_work.wait(lk, [&] { return m->stop || !m->todo.empty(); });
    if (m->todo.empty()) return;  // stop && drained
    std::shared_ptr<InflJob> job = m->todo.front();
    m->todo.pop_front();
    lk.unlock();
    std::string err;
    job->out.resize(job->raw.isize);
    bool ok = job->raw.isize == 0 ||
              inflate_block(job->raw, job->out.data(), err);
    lk.lock();
    if (!ok) job->err = err;
    job->done = true;
    m->cv_done.notify_all();
  }
}

// Top the fetch window back up. Runs on the consumer thread (sole owner of
// the FILE*); locks only around queue mutation, never around fread.
void mt_fill(Reader* r) {
  MtInflate* m = r->mt;
  while (true) {
    {
      std::lock_guard<std::mutex> lk(m->mu);
      if (m->fetch_eof || m->order.size() >= m->window) return;
    }
    auto job = std::make_shared<InflJob>();
    std::string err;
    int rc = fetch_raw_block(r->fh, job->raw, r->last_block_empty, err);
    std::lock_guard<std::mutex> lk(m->mu);
    if (rc <= 0) {
      m->fetch_eof = true;
      if (rc < 0) m->fetch_err = err;
      return;
    }
    m->order.push_back(job);
    m->todo.push_back(job);
    m->cv_work.notify_one();
  }
}

// MT replacement for the synchronous block append below: deliver the next
// inflated block, in fetch order, into the carry.
bool mt_next_block(Reader* r) {
  MtInflate* m = r->mt;
  mt_fill(r);
  std::shared_ptr<InflJob> job;
  {
    std::unique_lock<std::mutex> lk(m->mu);
    if (m->order.empty()) {
      if (!m->fetch_err.empty()) {
        r->err = m->fetch_err;
        return false;
      }
      r->eof = true;
      return true;
    }
    job = m->order.front();
    m->cv_done.wait(lk, [&] { return job->done; });
    m->order.pop_front();
  }
  if (!job->err.empty()) {
    r->err = job->err;
    return false;
  }
  if (r->carry_off > 0) {  // compact the carry before appending
    r->carry.erase(r->carry.begin(), r->carry.begin() + r->carry_off);
    r->carry_off = 0;
  }
  size_t old = r->carry.size();
  r->carry.resize(old + job->out.size());
  if (!job->out.empty())
    memcpy(r->carry.data() + old, job->out.data(), job->out.size());
  mt_fill(r);  // keep workers busy while the parser chews this block
  return true;
}

bool read_block(Reader* r) {
  if (r->mt) return mt_next_block(r);
  RawBlock b;
  int rc = fetch_raw_block(r->fh, b, r->last_block_empty, r->err);
  if (rc < 0) return false;
  if (rc == 0) {
    r->eof = true;
    return true;
  }
  // compact the carry before appending
  if (r->carry_off > 0) {
    r->carry.erase(r->carry.begin(), r->carry.begin() + r->carry_off);
    r->carry_off = 0;
  }
  size_t old = r->carry.size();
  r->carry.resize(old + b.isize);
  if (b.isize > 0 && !inflate_block(b, r->carry.data() + old, r->err))
    return false;
  return true;
}

// ensure >= n unconsumed bytes in carry; false on eof-before-n or error
bool ensure(Reader* r, size_t n) {
  while (r->carry.size() - r->carry_off < n) {
    if (r->eof) return false;
    if (!read_block(r)) return false;
  }
  return true;
}

// Compress one payload into a complete on-disk BGZF block (header +
// deflate stream + crc/isize tail). Pure function of (data, level) — the
// single-threaded and multi-threaded writers produce identical bytes.
bool compress_block(const uint8_t* data, size_t n, int level,
                    std::vector<uint8_t>& out, std::string& err) {
  std::vector<uint8_t> cdata(kMaxBlock);
  for (int attempt_level = level;; attempt_level = 0) {
    z_stream zs;
    memset(&zs, 0, sizeof(zs));
    if (deflateInit2(&zs, attempt_level, Z_DEFLATED, -15, 8,
                     Z_DEFAULT_STRATEGY) != Z_OK) {
      err = "deflateInit failed";
      return false;
    }
    zs.next_in = const_cast<uint8_t*>(data);
    zs.avail_in = uInt(n);
    zs.next_out = cdata.data();
    zs.avail_out = uInt(cdata.size());
    int rc = deflate(&zs, Z_FINISH);
    size_t clen = zs.total_out;
    deflateEnd(&zs);
    if (rc != Z_STREAM_END) {
      if (attempt_level != 0) continue;  // retry stored
      err = "deflate failed";
      return false;
    }
    size_t bsize = clen + 12 + 6 + 8;
    if (bsize > 65536) {
      if (attempt_level != 0) continue;
      err = "block too large even stored";
      return false;
    }
    uint8_t head[18] = {0x1f, 0x8b, 8,    4,    0, 0, 0, 0, 0,
                        0xff, 6,    0,    0x42, 0x43, 2, 0, 0, 0};
    uint16_t bs = uint16_t(bsize - 1);
    head[16] = uint8_t(bs & 0xff);
    head[17] = uint8_t(bs >> 8);
    uint32_t crc = crc32(0L, data, n);
    uint8_t tail[8] = {uint8_t(crc), uint8_t(crc >> 8), uint8_t(crc >> 16),
                       uint8_t(crc >> 24), uint8_t(n), uint8_t(n >> 8),
                       uint8_t(n >> 16), uint8_t(n >> 24)};
    out.clear();
    out.reserve(18 + clen + 8);
    out.insert(out.end(), head, head + 18);
    out.insert(out.end(), cdata.data(), cdata.data() + clen);
    out.insert(out.end(), tail, tail + 8);
    return true;
  }
}

bool flush_block(Writer* w, const uint8_t* data, size_t n) {
  std::vector<uint8_t> block;
  if (!compress_block(data, n, w->level, block, w->err)) return false;
  if (fwrite(block.data(), 1, block.size(), w->fh) != block.size()) {
    w->err = "write failed";
    return false;
  }
  return true;
}

inline int32_t rd_i32(const uint8_t* p) {
  int32_t v;
  memcpy(&v, p, 4);
  return v;
}
inline uint32_t rd_u32(const uint8_t* p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}
inline uint16_t rd_u16(const uint8_t* p) {
  uint16_t v;
  memcpy(&v, p, 2);
  return v;
}

// Extract a Z-type tag's value into out (NUL-terminated, truncated to w-1).
// graftguard: a tag that IS present but malformed — wrong type (not
// Z/H), empty value, or non-printable bytes — must be distinguishable
// from an absent tag, or the strict native path silently accepts
// records the Python engine refuses (faults.guard record_violation
// 'tag-shape'). Present-but-malformed writes this sentinel byte into
// the fixed-width slot; absent stays "" (faults.guard.TAG_MALFORMED
// mirrors the value).
static const char kTagMalformed = '\x01';

void find_z_tag(const uint8_t* tags, size_t n, const char* key, char* out,
                int w) {
  out[0] = '\0';
  size_t off = 0;
  while (off + 3 <= n) {
    char t0 = char(tags[off]), t1 = char(tags[off + 1]);
    char tc = char(tags[off + 2]);
    bool hit = (t0 == key[0] && t1 == key[1]);
    off += 3;
    size_t len = 0;
    switch (tc) {
      case 'A': case 'c': case 'C': len = 1; break;
      case 's': case 'S': len = 2; break;
      case 'i': case 'I': case 'f': len = 4; break;
      case 'Z': case 'H': {
        size_t e = off;
        while (e < n && tags[e] != 0) e++;
        if (hit) {
          size_t cnt = e - off;
          bool printable = cnt > 0;
          for (size_t i = off; i < e && printable; i++)
            printable = tags[i] >= 0x21 && tags[i] <= 0x7E;
          if (!printable) {
            out[0] = kTagMalformed;
            out[1] = '\0';
            return;
          }
          if (cnt > size_t(w - 1)) cnt = w - 1;
          memcpy(out, tags + off, cnt);
          out[cnt] = '\0';
          return;
        }
        off = e + 1;
        continue;
      }
      case 'B': {
        if (off + 5 > n) return;
        if (hit) {
          out[0] = kTagMalformed;
          out[1] = '\0';
          return;
        }
        char sub = char(tags[off]);
        uint32_t cnt = rd_u32(tags + off + 1);
        size_t esz = (sub == 'c' || sub == 'C') ? 1
                     : (sub == 's' || sub == 'S') ? 2 : 4;
        off += 5 + size_t(cnt) * esz;
        continue;
      }
      default:
        return;  // unknown tag type: stop scanning
    }
    if (hit) {  // present under a non-string type: malformed
      out[0] = kTagMalformed;
      out[1] = '\0';
      return;
    }
    off += len;
  }
}

// Locate the cd/ce/cB consensus per-base B-array tags in one tag-region
// walk (the duplex stage threads these raw molecular depths/errors/base
// histograms through to fgbio-unit ad/bd + exact-ce output,
// pipeline.calling._duplex_sidecar). Any integer subtype is accepted;
// values are widened/clamped to u16 at copy time.
struct BTagRef {
  const uint8_t* data = nullptr;
  uint32_t cnt = 0;
  char sub = 0;
};

// aux_len flag bit: the record's aux span carries the cB histogram
// (4n extra u16 after cd/ce). Mirrored in pipeline/ingest.py.
constexpr int32_t kAuxHasCb = 1 << 30;

void find_cdce_tags(const uint8_t* tags, size_t n, BTagRef& cd, BTagRef& ce,
                    BTagRef& cb) {
  size_t off = 0;
  while (off + 3 <= n) {
    char t0 = char(tags[off]), t1 = char(tags[off + 1]);
    char tc = char(tags[off + 2]);
    off += 3;
    switch (tc) {
      case 'A': case 'c': case 'C': off += 1; continue;
      case 's': case 'S': off += 2; continue;
      case 'i': case 'I': case 'f': off += 4; continue;
      case 'Z': case 'H': {
        while (off < n && tags[off] != 0) off++;
        off++;
        continue;
      }
      case 'B': {
        if (off + 5 > n) return;
        char sub = char(tags[off]);
        uint32_t cnt = rd_u32(tags + off + 1);
        size_t esz = (sub == 'c' || sub == 'C') ? 1
                     : (sub == 's' || sub == 'S') ? 2 : 4;
        if (off + 5 + size_t(cnt) * esz > n) return;
        if (t0 == 'c' && sub != 'f') {
          if (t1 == 'd') cd = BTagRef{tags + off + 5, cnt, sub};
          else if (t1 == 'e') ce = BTagRef{tags + off + 5, cnt, sub};
          else if (t1 == 'B') cb = BTagRef{tags + off + 5, cnt, sub};
        }
        off += 5 + size_t(cnt) * esz;
        continue;
      }
      default:
        return;  // unknown tag type: stop scanning
    }
  }
}

inline uint16_t btag_u16(const BTagRef& t, uint32_t i) {
  switch (t.sub) {
    case 'c': {
      int8_t v;
      std::memcpy(&v, t.data + i, 1);
      return uint16_t(v < 0 ? 0 : v);
    }
    case 'C':
      return t.data[i];
    case 's': {
      int16_t v;
      std::memcpy(&v, t.data + i * 2, 2);
      return uint16_t(v < 0 ? 0 : v);
    }
    case 'S': {
      uint16_t v;
      std::memcpy(&v, t.data + i * 2, 2);
      return v;
    }
    default: {  // i / I
      int32_t v;
      std::memcpy(&v, t.data + i * 4, 4);
      if (v < 0) v = 0;
      if (v > 65535) v = 65535;
      return uint16_t(v);
    }
  }
}

// ---- shared columnar record emission --------------------------------------

}  // namespace (reopened below: the stream reader is part of the C ABI)

extern "C" int64_t bamio_read(Reader* r, uint8_t* buf, int64_t n);

namespace {

// Output arrays + cursors for one columnar batch (the bamio_parse_records2
// surface). emit_record_body decodes one raw record body into the next slot.
struct ColumnarOut {
  int32_t* ref_id;
  int32_t* pos;
  uint16_t* flag;
  uint8_t* mapq;
  int32_t* l_seq;
  int32_t* next_ref;
  int32_t* next_pos;
  int32_t* tlen;
  uint16_t* n_cigar;
  uint8_t* seq_codes;
  uint8_t* quals;
  int64_t var_cap;
  int64_t* var_off;
  uint32_t* cigar;
  int64_t cigar_cap;
  int64_t* cigar_off;
  char* qname;
  int qname_w;
  char* mi;
  int mi_w;
  char* rx;
  int rx_w;
  int64_t max_records;
  int64_t vused = 0, cused = 0, nrec = 0;
  int32_t* ref_span;
  int32_t* left_clip;
  int32_t* right_clip;
  uint8_t* cigar_flags;
  // cd/ce aux planes: per record, cd values then ce values (aux_len[i]
  // u16 each) at aux[aux_off[i]]; aux_len 0 = tags absent/unusable.
  // aux_cap = 2 * var_cap keeps "fits in var" implying "fits in aux"
  // whenever cnt <= l_seq (larger counts are treated as absent).
  uint16_t* aux = nullptr;
  int64_t aux_cap = 0;
  int64_t* aux_off = nullptr;
  int32_t* aux_len = nullptr;
  int64_t aux_used = 0;
};

bool record_fits(const uint8_t* p, ColumnarOut& o) {
  int32_t lseq = rd_i32(p + 16);
  uint16_t ncig = rd_u16(p + 12);
  return o.nrec < o.max_records && o.vused + lseq <= o.var_cap &&
         o.cused + ncig <= o.cigar_cap;
}

void emit_record_body(const uint8_t* p, size_t bs, ColumnarOut& o) {
  const int64_t nrec = o.nrec;
  int32_t lseq = rd_i32(p + 16);
  uint16_t ncig = rd_u16(p + 12);
  uint8_t l_qname = p[8];
  o.ref_id[nrec] = rd_i32(p + 0);
  o.pos[nrec] = rd_i32(p + 4);
  o.mapq[nrec] = p[9];
  o.n_cigar[nrec] = ncig;
  o.flag[nrec] = rd_u16(p + 14);
  o.l_seq[nrec] = lseq;
  o.next_ref[nrec] = rd_i32(p + 20);
  o.next_pos[nrec] = rd_i32(p + 24);
  o.tlen[nrec] = rd_i32(p + 28);
  size_t off = 32;
  {
    size_t cnt = l_qname - 1;
    if (cnt > size_t(o.qname_w - 1)) cnt = o.qname_w - 1;
    memcpy(o.qname + nrec * o.qname_w, p + off, cnt);
    o.qname[nrec * o.qname_w + cnt] = '\0';
  }
  off += l_qname;
  memcpy(o.cigar + o.cused, p + off, size_t(ncig) * 4);
  o.cigar_off[nrec] = o.cused;
  {
    int32_t rspan = 0;
    uint8_t cf = 0;
    const uint32_t* cg = o.cigar + o.cused;
    for (uint16_t k = 0; k < ncig; k++) {
      uint32_t op = cg[k] & 0xF, len = cg[k] >> 4;
      switch (op) {
        case 0: case 7: case 8: rspan += int32_t(len); break;  // M,=,X
        case 2: rspan += int32_t(len); cf |= 1; break;         // D
        case 3: rspan += int32_t(len); break;                  // N
        case 1: cf |= 1; break;                                // I
        case 5: cf |= 2; break;                                // H
        default: break;                                        // S,P
      }
    }
    int32_t lcl = 0, rcl = 0;
    if (ncig) {
      if ((cg[0] & 0xF) == 4) lcl = int32_t(cg[0] >> 4);
      if ((cg[ncig - 1] & 0xF) == 4) rcl = int32_t(cg[ncig - 1] >> 4);
    }
    o.ref_span[nrec] = rspan;
    o.left_clip[nrec] = lcl;
    o.right_clip[nrec] = rcl;
    o.cigar_flags[nrec] = cf;
  }
  o.cused += ncig;
  off += size_t(ncig) * 4;
  o.var_off[nrec] = o.vused;
  const uint8_t* sp = p + off;
  for (int32_t i = 0; i < lseq; i++) {
    uint8_t b = sp[i >> 1];
    uint8_t code = (i & 1) ? (b & 0xf) : (b >> 4);
    o.seq_codes[o.vused + i] = uint8_t(kNt16ToCode[code]);
  }
  off += (lseq + 1) / 2;
  memcpy(o.quals + o.vused, p + off, lseq);
  off += lseq;
  o.vused += lseq;
  find_z_tag(p + off, bs - off, "MI", o.mi + nrec * o.mi_w, o.mi_w);
  find_z_tag(p + off, bs - off, "RX", o.rx + nrec * o.rx_w, o.rx_w);
  if (o.aux != nullptr) {
    o.aux_off[nrec] = o.aux_used;
    o.aux_len[nrec] = 0;
    BTagRef cd, ce, cb;
    find_cdce_tags(p + off, bs - off, cd, ce, cb);
    if (cd.data && ce.data && cd.cnt == ce.cnt && cd.cnt &&
        int64_t(cd.cnt) <= int64_t(lseq) &&
        o.aux_used + 2 * int64_t(cd.cnt) <= o.aux_cap) {
      uint16_t* dst = o.aux + o.aux_used;
      for (uint32_t i = 0; i < cd.cnt; i++) dst[i] = btag_u16(cd, i);
      dst += cd.cnt;
      for (uint32_t i = 0; i < ce.cnt; i++) dst[i] = btag_u16(ce, i);
      o.aux_len[nrec] = int32_t(cd.cnt);
      o.aux_used += 2 * int64_t(cd.cnt);
      // cB histogram plane (4n values) appended when present + well
      // formed; flagged via kAuxHasCb in aux_len (the layout stays
      // [cd(n); ce(n)] for rows without it)
      if (cb.data && cb.cnt == 4 * cd.cnt &&
          o.aux_used + 4 * int64_t(cd.cnt) <= o.aux_cap) {
        dst += ce.cnt;
        for (uint32_t i = 0; i < cb.cnt; i++) dst[i] = btag_u16(cb, i);
        o.aux_len[nrec] |= kAuxHasCb;
        o.aux_used += 4 * int64_t(cd.cnt);
      }
    }
  }
  o.nrec++;
}

// graftguard structural validation: a record whose declared field
// lengths cannot fit its block size must be refused HERE — every
// downstream consumer (emit_record_body, ref_end_of_body, the tag
// walkers) indexes the body by these fields and would read past the
// buffer on a length-field lie. Byte-identical rule + message to the
// Python mirror (faults.guard.check_record_body) so both decode
// engines refuse the same record at the same index.
const char* body_check(const uint8_t* p, size_t bs) {
  static const char* kCorrupt = "corrupt record body (field/length mismatch)";
  if (bs < 32) return kCorrupt;
  uint8_t l_qname = p[8];
  uint16_t ncig = rd_u16(p + 12);
  int32_t lseq = rd_i32(p + 16);
  if (l_qname < 1 || lseq < 0) return kCorrupt;
  int64_t need = 32 + int64_t(l_qname) + 4 * int64_t(ncig) +
                 (int64_t(lseq) + 1) / 2 + int64_t(lseq);
  if (need > int64_t(bs)) return kCorrupt;
  return nullptr;
}

// Read one raw record body (sans block_size) from the stream.
// Returns 1 ok, 0 clean EOF, -1 error (r->err set).
int read_record_body(Reader* r, std::vector<uint8_t>& body) {
  uint8_t szbuf[4];
  int64_t got = bamio_read(r, szbuf, 4);
  if (got == 0) return 0;
  if (got != 4) {
    r->err = r->err.empty() ? "truncated record size" : r->err;
    return -1;
  }
  int32_t bs = rd_i32(szbuf);
  if (bs < 32 || bs > (1 << 28)) {
    r->err = "corrupt record size";
    return -1;
  }
  body.resize(bs);
  if (bamio_read(r, body.data(), bs) != bs) {
    r->err = r->err.empty() ? "truncated record body" : r->err;
    return -1;
  }
  const char* reason = body_check(body.data(), body.size());
  if (reason != nullptr) {
    r->err = reason;
    return -1;
  }
  return 1;
}

// ---- streaming coordinate MI-grouper --------------------------------------
//
// C-side equivalent of pipeline.calling.stream_mi_groups grouping
// 'coordinate' (flush a family once the sweep passes margin bases beyond
// its last read; insertion-ordered open set exactly like a Python dict;
// refragmented families counted, missing MI is an error). Families come
// back as CONTIGUOUS record runs inside otherwise-normal columnar batches,
// so the Python layer does no per-record grouping work at all.

struct OpenGroup {
  std::vector<std::vector<uint8_t>> bodies;
  int32_t ref_id = -1;
  int64_t max_end = -1;
  std::string key;
  bool live = true;
};

struct Grouper {
  int64_t margin = 10000;
  int64_t stride = 2500;
  bool strip = false;
  // adjacent mode (margin < 0 at bamio_group_start): groups are
  // delimited by MI change alone — exact for MI-contiguous input
  // whatever the template geometry (a cross-contig or wide-insert pair
  // would trip the coordinate sweep's position heuristics)
  bool adjacent = false;
  // insertion-ordered open set: slots + key->slot map; dead slots are
  // compacted during sweeps (mirrors Python dict iteration order)
  std::vector<OpenGroup> open;
  std::unordered_map<std::string, size_t> index;
  std::deque<OpenGroup> ready;
  std::unordered_set<uint64_t> flushed;
  int64_t refragmented = 0;
  int32_t last_ref = -1;
  int64_t last_pos = -(int64_t(1) << 62);
  bool source_done = false;
  std::string err;
};

int64_t ref_end_of_body(const uint8_t* p) {
  int64_t pos = rd_i32(p + 4);
  uint16_t ncig = rd_u16(p + 12);
  uint8_t l_qname = p[8];
  const uint8_t* cg = p + 32 + l_qname;
  int64_t span = 0;
  for (uint16_t k = 0; k < ncig; k++) {
    uint32_t v = rd_u32(cg + 4 * k);
    uint32_t op = v & 0xF;
    if (op == 0 || op == 2 || op == 3 || op == 7 || op == 8) span += v >> 4;
  }
  return pos + span;
}

// Full-length Z-tag lookup with a found flag (find_z_tag cannot
// distinguish an absent tag from an empty value, and its fixed-width
// output would truncate long grouping keys into silent merges).
bool z_tag_find(const uint8_t* tags, size_t n, const char* key,
                std::string& out) {
  size_t off = 0;
  while (off + 3 <= n) {
    char t0 = char(tags[off]), t1 = char(tags[off + 1]);
    char tc = char(tags[off + 2]);
    off += 3;
    size_t len = 0;
    switch (tc) {
      case 'A': case 'c': case 'C': len = 1; break;
      case 's': case 'S': len = 2; break;
      case 'i': case 'I': case 'f': len = 4; break;
      case 'Z': case 'H': {
        size_t e = off;
        while (e < n && tags[e] != 0) e++;
        if (t0 == key[0] && t1 == key[1]) {
          out.assign(reinterpret_cast<const char*>(tags + off), e - off);
          return true;
        }
        off = e + 1;
        continue;
      }
      case 'B': {
        if (off + 5 > n) return false;
        char sub = char(tags[off]);
        uint32_t cnt = rd_u32(tags + off + 1);
        size_t esz = (sub == 'c' || sub == 'C') ? 1
                     : (sub == 's' || sub == 'S') ? 2 : 4;
        off += 5 + size_t(cnt) * esz;
        continue;
      }
      default:
        return false;  // unknown tag type: stop scanning
    }
    off += len;
  }
  return false;
}

// MI key of one record body; returns false when the tag is ABSENT (an
// empty value is a legal key, matching the Python streamer).
bool mi_key_of_body(const uint8_t* p, size_t bs, bool strip,
                    std::string& key) {
  uint16_t ncig = rd_u16(p + 12);
  int32_t lseq = rd_i32(p + 16);
  uint8_t l_qname = p[8];
  size_t off = 32 + l_qname + size_t(ncig) * 4 + (lseq + 1) / 2 + lseq;
  if (off >= bs) return false;
  if (!z_tag_find(p + off, bs - off, "MI", key)) return false;
  if (strip) {
    size_t slash = key.find('/');
    if (slash != std::string::npos) key.resize(slash);
  }
  return true;
}

void grouper_sweep(Grouper& g, int32_t ref_id, int64_t pos) {
  // flush done groups in insertion order, then compact dead slots
  bool any_dead = false;
  for (auto& og : g.open) {
    if (!og.live) continue;
    if (og.ref_id != ref_id || og.max_end + g.margin < pos) {
      g.flushed.insert(fnv1a64(og.key));
      g.index.erase(og.key);
      og.live = false;
      g.ready.push_back(std::move(og));
      any_dead = true;
    }
  }
  if (any_dead) {
    std::vector<OpenGroup> kept;
    kept.reserve(g.open.size());
    for (auto& og : g.open)
      if (og.live) {
        g.index[og.key] = kept.size();
        kept.push_back(std::move(og));
      }
    g.open.swap(kept);
  }
  g.last_ref = ref_id;
  g.last_pos = pos;
}

// Feed one record; returns false on missing MI (g.err set to the qname).
bool grouper_feed(Grouper& g, std::vector<uint8_t>&& body) {
  const uint8_t* p = body.data();
  std::string key;
  if (!mi_key_of_body(p, body.size(), g.strip, key)) {
    uint8_t l_qname = p[8];
    g.err.assign(reinterpret_cast<const char*>(p + 32),
                 l_qname ? l_qname - 1 : 0);
    return false;
  }
  int32_t ref_id = rd_i32(p + 0);
  int64_t pos = rd_i32(p + 4);
  if (g.adjacent) {
    if (!g.open.empty() && g.index.find(key) == g.index.end()) {
      // MI changed: flush every live group (at most one in this mode)
      for (auto& og : g.open)
        if (og.live) {
          g.flushed.insert(fnv1a64(og.key));
          og.live = false;
          g.ready.push_back(std::move(og));
        }
      g.open.clear();
      g.index.clear();
    }
  } else if (pos >= 0 && !g.open.empty() &&
             (ref_id != g.last_ref || pos - g.last_pos >= g.stride)) {
    grouper_sweep(g, ref_id, pos);
  }
  auto it = g.index.find(key);
  if (it == g.index.end()) {
    if (g.flushed.count(fnv1a64(key))) g.refragmented++;
    g.index[key] = g.open.size();
    g.open.emplace_back();
    g.open.back().key = key;
    it = g.index.find(key);
  }
  OpenGroup& og = g.open[it->second];
  if (pos >= 0 && !g.adjacent) {  // adjacent mode never reads max_end
    int64_t end = ref_end_of_body(p);
    if (og.max_end < 0 || og.ref_id != ref_id) {
      og.ref_id = ref_id;
      og.max_end = end;
    } else if (end > og.max_end) {
      og.max_end = end;
    }
  }
  og.bodies.push_back(std::move(body));
  return true;
}

}  // namespace

extern "C" {

Reader* bamio_open(const char* path, char* err, int errlen) {
  Reader* r = new Reader();
  r->fh = fopen(path, "rb");
  if (!r->fh) {
    snprintf(err, errlen, "cannot open %s", path);
    delete r;
    return nullptr;
  }
  return r;
}

// Open with `threads` parallel inflate workers (<=1 = plain bamio_open).
// The handle is interchangeable with bamio_open's everywhere (bamio_read,
// the columnar parsers, the grouper): only block decompression changes,
// the delivered byte stream is identical.
Reader* bamio_open_mt(const char* path, int threads, char* err, int errlen) {
  Reader* r = bamio_open(path, err, errlen);
  if (!r || threads <= 1) return r;
  r->mt = new MtInflate();
  for (int i = 0; i < threads; i++)
    r->mt->workers.emplace_back(mt_inflate_worker, r->mt);
  return r;
}

// Read up to n decompressed bytes. Returns bytes read (0 at EOF), -1 error.
int64_t bamio_read(Reader* r, uint8_t* buf, int64_t n) {
  int64_t total = 0;
  while (total < n) {
    size_t avail = r->carry.size() - r->carry_off;
    if (avail == 0) {
      if (r->eof) break;
      if (!read_block(r)) return -1;
      continue;
    }
    size_t take = size_t(n - total) < avail ? size_t(n - total) : avail;
    memcpy(buf + total, r->carry.data() + r->carry_off, take);
    r->carry_off += take;
    total += take;
  }
  return total;
}

const char* bamio_error(Reader* r) { return r->err.c_str(); }

void bamio_close(Reader* r) {
  if (r->mt) {
    {
      std::lock_guard<std::mutex> lk(r->mt->mu);
      r->mt->stop = true;
      r->mt->todo.clear();  // abandoned work: nothing will be delivered
    }
    r->mt->cv_work.notify_all();
    for (auto& t : r->mt->workers) t.join();
    delete r->mt;
  }
  if (r->fh) fclose(r->fh);
  delete r;
}

// Parse up to max_records alignment records into columnar arrays.
// Fixed per-record: ref_id, pos, flag, mapq, l_seq, next_ref, next_pos, tlen,
// n_cigar. Variable: seq codes + quals at var_off[i] (l_seq[i] bytes each,
// capacity var_cap), cigar ops at cigar_off[i] (n_cigar u32), qname/mi/rx
// fixed-width NUL-terminated strings. Also emits the per-record CIGAR
// digest the Python hot loops otherwise recompute per record: ref_span
// (reference bases consumed: M/D/N/=/X), left_clip/right_clip (terminal
// softclip lengths), cigar_flags (bit0 = has I/D, bit1 = has hardclip).
// Returns records parsed, -1 on error. Stops early (returning fewer) when
// a capacity would be exceeded; the blocking record is buffered internally
// and returned by the next call. (The numeric suffix versions the
// signature: loading a stale .so fails symbol lookup and triggers a
// rebuild instead of corrupting memory through a mismatched call. "3"
// added the cd/ce aux planes with per-record aux_off/aux_len; "4" appends
// the optional 4n cB histogram run, flagged via kAuxHasCb in aux_len —
// size aux_cap at 6*var_cap u16 elements so a var-capacity fit implies an
// aux fit even when every record carries cB. See ColumnarOut.)
int64_t bamio_parse_records4(
    Reader* r, int64_t max_records,
    int32_t* ref_id, int32_t* pos, uint16_t* flag, uint8_t* mapq,
    int32_t* l_seq, int32_t* next_ref, int32_t* next_pos, int32_t* tlen,
    uint16_t* n_cigar,
    uint8_t* seq_codes, uint8_t* quals, int64_t var_cap, int64_t* var_off,
    uint32_t* cigar, int64_t cigar_cap, int64_t* cigar_off,
    char* qname, int qname_w, char* mi, int mi_w, char* rx, int rx_w,
    int32_t* ref_span, int32_t* left_clip, int32_t* right_clip,
    uint8_t* cigar_flags,
    uint16_t* aux, int64_t aux_cap, int64_t* aux_off, int32_t* aux_len) {
  ColumnarOut o{ref_id, pos, flag, mapq, l_seq, next_ref, next_pos, tlen,
                n_cigar, seq_codes, quals, var_cap, var_off, cigar,
                cigar_cap, cigar_off, qname, qname_w, mi, mi_w, rx, rx_w,
                max_records, 0, 0, 0,
                ref_span, left_clip, right_clip, cigar_flags,
                aux, aux_cap, aux_off, aux_len};
  std::vector<uint8_t> body;
  while (o.nrec < max_records) {
    if (!r->pending.empty()) {
      body.swap(r->pending);
      r->pending.clear();
    } else {
      int rc = read_record_body(r, body);
      if (rc == 0) break;
      if (rc < 0)
        // mid-batch corruption: hand the already-parsed prefix back so
        // the caller can account the exact failing record index; the
        // pending error stays in r->err (bamio_error) and the caller
        // must not parse again. A clean leading failure keeps -1.
        return o.nrec > 0 ? o.nrec : -1;
    }
    if (!record_fits(body.data(), o)) {
      r->pending.swap(body);  // doesn't fit: hand back next call
      break;
    }
    emit_record_body(body.data(), body.size(), o);
  }
  return o.nrec;
}

Writer* bamio_create(const char* path, int level, char* err, int errlen) {
  Writer* w = new Writer();
  w->fh = fopen(path, "wb");
  w->level = level;
  if (!w->fh) {
    snprintf(err, errlen, "cannot create %s", path);
    delete w;
    return nullptr;
  }
  w->buf.reserve(65280);
  return w;
}

int bamio_write(Writer* w, const uint8_t* data, int64_t n) {
  return buffered_write(w->buf, data, n, [&] {
    if (!flush_block(w, w->buf.data(), w->buf.size())) return false;
    w->buf.clear();
    return true;
  });
}

const char* bamio_writer_error(Writer* w) { return w->err.c_str(); }

int bamio_finish(Writer* w) {
  int rc = 0;
  if (!w->buf.empty()) {
    if (!flush_block(w, w->buf.data(), w->buf.size())) rc = -1;
    w->buf.clear();
  }
  if (rc == 0 && fwrite(kEofBlock, 1, 28, w->fh) != 28) rc = -1;
  if (fclose(w->fh) != 0) rc = -1;
  w->fh = nullptr;
  delete w;
  return rc;
}

// ---- multi-threaded writer ABI (byte-identical output to the above) ----

MtWriter* bamio_create_mt(const char* path, int level, int threads, char* err,
                          int errlen) {
  if (threads < 1) threads = 1;
  if (threads > 64) threads = 64;
  MtWriter* w = new MtWriter();
  w->fh = fopen(path, "wb");
  w->level = level;
  if (!w->fh) {
    snprintf(err, errlen, "cannot create %s", path);
    delete w;
    return nullptr;
  }
  w->buf.reserve(65280);
  w->max_queue = size_t(threads) * 4;
  for (int i = 0; i < threads; ++i)
    w->workers.emplace_back(mt_worker, w);
  return w;
}

int bamio_write_mt(MtWriter* w, const uint8_t* data, int64_t n) {
  if (!w->err.empty()) return -1;
  return buffered_write(w->buf, data, n, [&] {
    std::vector<uint8_t> payload;
    payload.reserve(65280);
    payload.swap(w->buf);
    w->buf.reserve(65280);
    return mt_submit(w, std::move(payload));
  });
}

const char* bamio_writer_error_mt(MtWriter* w) { return w->err.c_str(); }

int bamio_finish_mt(MtWriter* w) {
  // a recorded write/compress failure must fail the finish too — appending
  // the EOF marker to a truncated stream would make corruption look like a
  // validly terminated file
  int rc = w->err.empty() ? 0 : -1;
  if (rc == 0 && !w->buf.empty()) {
    if (!mt_submit(w, std::move(w->buf))) rc = -1;
  }
  if (rc == 0 && !mt_drain(w, true)) rc = -1;
  if (rc == 0 && fwrite(kEofBlock, 1, 28, w->fh) != 28) rc = -1;
  if (fclose(w->fh) != 0) rc = -1;
  w->fh = nullptr;
  delete w;  // joins workers
  return rc;
}

// ---- streaming coordinate MI-grouping (C ABI) -----------------------------

Grouper* bamio_group_start(int64_t margin, int strip) {
  Grouper* g = new Grouper();
  if (margin < 0) {  // sentinel: adjacent (MI-change-delimited) mode
    g->adjacent = true;
    margin = 0;
  }
  g->margin = margin;
  g->stride = margin / 4 > 0 ? margin / 4 : 1;
  g->strip = strip != 0;
  return g;
}

const char* bamio_group_error(Grouper* g) { return g->err.c_str(); }

int64_t bamio_group_refragmented(Grouper* g) { return g->refragmented; }

void bamio_group_free(Grouper* g) { delete g; }

// Grouped columnar parse: the bamio_parse_records4 output surface with
// records reordered into CONTIGUOUS whole-family runs (coordinate-sorted
// input; flush-margin semantics of pipeline.calling.stream_mi_groups
// 'coordinate', including insertion-order flushing and refragmentation
// counting). fam_nrec[i] records of family i are adjacent; fam_mi holds
// each family's (optionally /-stripped) MI key. Returns records emitted
// (0 = stream complete), -1 stream error (bamio_error), -2 record without
// an MI tag (bamio_group_error -> offending qname), -3 the next family
// alone exceeds a capacity (retry with larger buffers).
int64_t bamio_parse_grouped3(
    Reader* r, Grouper* g, int64_t max_records,
    int32_t* ref_id, int32_t* pos, uint16_t* flag, uint8_t* mapq,
    int32_t* l_seq, int32_t* next_ref, int32_t* next_pos, int32_t* tlen,
    uint16_t* n_cigar,
    uint8_t* seq_codes, uint8_t* quals, int64_t var_cap, int64_t* var_off,
    uint32_t* cigar, int64_t cigar_cap, int64_t* cigar_off,
    char* qname, int qname_w, char* mi, int mi_w, char* rx, int rx_w,
    int32_t* ref_span, int32_t* left_clip, int32_t* right_clip,
    uint8_t* cigar_flags,
    uint16_t* aux, int64_t aux_cap, int64_t* aux_off, int32_t* aux_len,
    char* fam_mi, int fam_mi_w, int32_t* fam_nrec, int64_t fam_cap,
    int64_t* n_fams) {
  ColumnarOut o{ref_id, pos, flag, mapq, l_seq, next_ref, next_pos, tlen,
                n_cigar, seq_codes, quals, var_cap, var_off, cigar,
                cigar_cap, cigar_off, qname, qname_w, mi, mi_w, rx, rx_w,
                max_records, 0, 0, 0,
                ref_span, left_clip, right_clip, cigar_flags,
                aux, aux_cap, aux_off, aux_len};
  std::vector<uint8_t> body;
  int64_t fams = 0;
  bool batch_full = false;
  while (!batch_full) {
    while (!g->ready.empty() && fams < fam_cap) {
      OpenGroup& og = g->ready.front();
      int64_t need_v = 0, need_c = 0;
      for (auto& b : og.bodies) {
        need_v += rd_i32(b.data() + 16);
        need_c += rd_u16(b.data() + 12);
      }
      if (o.nrec + int64_t(og.bodies.size()) > max_records ||
          o.vused + need_v > var_cap || o.cused + need_c > cigar_cap) {
        if (o.nrec == 0) return -3;  // one family bigger than the buffers
        batch_full = true;
        break;  // family stays queued for the next call
      }
      for (auto& b : og.bodies) emit_record_body(b.data(), b.size(), o);
      size_t cnt = og.key.size();
      if (cnt > size_t(fam_mi_w - 1)) cnt = size_t(fam_mi_w - 1);
      memcpy(fam_mi + fams * fam_mi_w, og.key.data(), cnt);
      fam_mi[fams * fam_mi_w + cnt] = '\0';
      fam_nrec[fams] = int32_t(og.bodies.size());
      fams++;
      g->ready.pop_front();
    }
    if (batch_full || o.nrec >= max_records || fams >= fam_cap) break;
    if (g->source_done && g->ready.empty()) break;
    if (g->source_done) continue;
    int rc = read_record_body(r, body);
    if (rc < 0) return -1;
    if (rc == 0) {
      g->source_done = true;
      // final flush: remaining open groups in insertion order
      for (auto& og : g->open)
        if (og.live) {
          og.live = false;
          g->ready.push_back(std::move(og));
        }
      g->open.clear();
      g->index.clear();
      continue;
    }
    if (!grouper_feed(*g, std::move(body))) return -2;
    body = std::vector<uint8_t>();  // reset the moved-from buffer
  }
  *n_fams = fams;
  return o.nrec;
}

}  // extern "C"

// ---- k-way raw-record merge (pipeline/extsort.py 'native' engine) ---------
//
// Merge sorted spill runs of encoded BAM records without any per-record
// Python: each run is an already-open Reader positioned just past its
// header, the output an already-open (single- or multi-threaded) BGZF
// writer. The comparator is EXACTLY pipeline.extsort.raw_coordinate_key's
// tuple order — (ref_id or 1<<30, pos or 1<<30, qname bytes, flag) — and
// ties prefer the LOWEST run index, matching heapq.merge's iterator-order
// stability, so the merged byte stream is identical to the Python
// engine's. Output rides the writer's normal 65280-byte block chunking,
// so the BGZF container is byte-identical too.

namespace {

struct MergeStream {
  Reader* r = nullptr;
  std::vector<uint8_t> rec;  // current record incl. its 4-byte prefix
  bool done = false;
  int64_t kref = 0, kpos = 0;
  int32_t qlen = 0;
  uint16_t kflag = 0;
};

// Pull the next record into s.rec; false on EOF or error (err set).
bool merge_advance(MergeStream& s, std::string& err) {
  uint8_t szbuf[4];
  int64_t got = bamio_read(s.r, szbuf, 4);
  if (got == 0) {
    s.done = true;
    return false;
  }
  if (got < 0) {
    err = s.r->err.empty() ? "read failed" : s.r->err;
    return false;
  }
  if (got < 4) {
    err = "truncated record size in spill run";
    return false;
  }
  int32_t bs;
  memcpy(&bs, szbuf, 4);
  if (bs < 32 || bs > (1 << 28)) {  // io/bam.py MIN/MAX_RECORD_SIZE
    err = "corrupt record size in spill run";
    return false;
  }
  s.rec.resize(size_t(bs) + 4);
  memcpy(s.rec.data(), szbuf, 4);
  if (bamio_read(s.r, s.rec.data() + 4, bs) != bs) {
    err = "truncated record body in spill run";
    return false;
  }
  const uint8_t* p = s.rec.data();
  int32_t ref, pos;
  memcpy(&ref, p + 4, 4);
  memcpy(&pos, p + 8, 4);
  s.kref = ref >= 0 ? ref : (int64_t(1) << 30);
  s.kpos = pos >= 0 ? pos : (int64_t(1) << 30);
  memcpy(&s.kflag, p + 18, 2);
  const int32_t lq = p[12];
  s.qlen = lq > 0 ? lq - 1 : 0;
  return true;
}

// strict-less on the raw_coordinate_key tuple (qname bytes compare like
// Python bytes: memcmp then shorter-prefix-first).
inline bool merge_less(const MergeStream& a, const MergeStream& b) {
  if (a.kref != b.kref) return a.kref < b.kref;
  if (a.kpos != b.kpos) return a.kpos < b.kpos;
  const int32_t n = a.qlen < b.qlen ? a.qlen : b.qlen;
  const int c = memcmp(a.rec.data() + 36, b.rec.data() + 36, size_t(n));
  if (c != 0) return c < 0;
  if (a.qlen != b.qlen) return a.qlen < b.qlen;
  return a.kflag < b.kflag;
}

}  // namespace

extern "C" {

// Merge n_runs sorted runs into `writer` (a Writer*, or an MtWriter* when
// writer_mt != 0 — its deflate worker pool is what the merge's BGZF
// compression rides on multi-core hosts). Readers must be positioned just
// past their BAM headers. Returns records written, or -1 with `err`
// filled. write_s (optional) accumulates the seconds spent inside the
// writer calls — the deflate/IO share of the merge, reported apart from
// the pure merge loop for the sort_write sub-attribution.
int64_t bamio_merge_runs(void** readers, int32_t n_runs, void* writer,
                         int32_t writer_mt, char* err, int32_t errlen,
                         double* write_s) {
  using clock = std::chrono::steady_clock;
  std::vector<MergeStream> streams(static_cast<size_t>(n_runs));
  std::string serr;
  for (int32_t i = 0; i < n_runs; ++i) {
    streams[size_t(i)].r = static_cast<Reader*>(readers[i]);
    if (!merge_advance(streams[size_t(i)], serr) &&
        !streams[size_t(i)].done) {
      snprintf(err, size_t(errlen), "run %d: %s", i, serr.c_str());
      return -1;
    }
  }
  std::vector<uint8_t> outbuf;
  outbuf.reserve(1 << 20);
  double wsec = 0.0;
  auto flush_out = [&]() -> bool {
    if (outbuf.empty()) return true;
    const auto t0 = clock::now();
    int rc;
    if (writer_mt)
      rc = bamio_write_mt(static_cast<MtWriter*>(writer), outbuf.data(),
                          int64_t(outbuf.size()));
    else
      rc = bamio_write(static_cast<Writer*>(writer), outbuf.data(),
                       int64_t(outbuf.size()));
    wsec += std::chrono::duration<double>(clock::now() - t0).count();
    outbuf.clear();
    return rc == 0;
  };
  int64_t n_out = 0;
  for (;;) {
    int32_t best = -1;
    for (int32_t i = 0; i < n_runs; ++i) {
      MergeStream& s = streams[size_t(i)];
      if (s.done) continue;
      if (best < 0 || merge_less(s, streams[size_t(best)])) best = i;
    }
    if (best < 0) break;
    MergeStream& s = streams[size_t(best)];
    outbuf.insert(outbuf.end(), s.rec.begin(), s.rec.end());
    ++n_out;
    if (outbuf.size() >= (1 << 20) && !flush_out()) {
      snprintf(err, size_t(errlen), "merge output write failed");
      return -1;
    }
    if (!merge_advance(s, serr) && !s.done) {
      snprintf(err, size_t(errlen), "run %d: %s", best, serr.c_str());
      return -1;
    }
  }
  if (!flush_out()) {
    snprintf(err, size_t(errlen), "merge output write failed");
    return -1;
  }
  if (write_s) *write_s = wsec;
  return n_out;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Molecular-encode digest: the C twin of the per-record pass in
// ops.encode.encode_molecular_families. The grouper above already hands
// families back as contiguous columnar runs; the scan below walks each run
// once, replicating the Python pass-1 semantics exactly (template pairing by
// fixed-width qname bytes with last-record-wins (qname, role) slots, RX
// majority with first-insertion tie-break, per-slot orientation votes,
// lo/hi window over every kept record), so the Python layer never touches
// individual records on the hot path. Fill then writes the [F, T, 2, W]
// tensors with straight memcpys.

namespace {

inline uint64_t enc_hash(const uint8_t* p, size_t n) {
  return fnv1a64(p, n);  // shared byte-loop hash (top of file)
}

// Fixed-width fields are NUL-padded from NUL-terminated values, so hashing
// and comparing strnlen+1 bytes is equivalent to the full width (the
// included NUL stops a prefix from matching a longer name) at a fraction
// of the byte work — qname_width is 256 for ~35-char names.
inline size_t enc_keylen(const uint8_t* p, size_t width) {
  size_t n = strnlen(reinterpret_cast<const char*>(p), width);
  return n < width ? n + 1 : width;
}

// Generation-stamped open-addressing scratch reused across families: reset()
// is O(1) except when capacity grows, so a 64k-record batch of small
// families pays no per-family clearing.
struct EncScratch {
  std::vector<int64_t> tbl_key;   // record index whose qname defines the entry
  std::vector<int32_t> tbl_ti;    // template row, -1 while est-only
  std::vector<uint32_t> tbl_gen;
  std::vector<int64_t> rtbl_key;  // record index whose RX defines the entry
  std::vector<int32_t> rtbl_idx;  // index into rx_* insertion-ordered lists
  std::vector<uint32_t> rtbl_gen;
  std::vector<int64_t> rx_count;
  std::vector<int64_t> rx_first;  // first record carrying this RX
  std::vector<int64_t> slot_rec;   // (ti, role) -> last record, -1 empty
  std::vector<uint8_t> slot_state;  // bit0 present, bit1 reverse-strand
  uint32_t gen = 0;
  size_t mask = 0;

  void reset(size_t nrec) {
    size_t cap = 16;
    while (cap < nrec * 2) cap <<= 1;
    if (cap > tbl_key.size()) {
      tbl_key.assign(cap, 0);
      tbl_ti.assign(cap, 0);
      tbl_gen.assign(cap, 0);
      rtbl_key.assign(cap, 0);
      rtbl_idx.assign(cap, 0);
      rtbl_gen.assign(cap, 0);
      gen = 0;
    }
    mask = tbl_key.size() - 1;
    gen++;
    rx_count.clear();
    rx_first.clear();
    slot_rec.clear();
    slot_state.clear();
  }
};

}  // namespace

extern "C" {

// Pass 1 over contiguous family runs [fam_start[f], fam_start[f]+fam_nrec[f]).
// Per record j: out_keep[j] 0 = dropped, 1 = direct-placed, 2 = pending
// indel (indel_policy 1 = 'align'); out_ti/out_role give the template slot.
// Per family f: out_lo/out_window (-1 when no record places), out_ntpl
// (distinct templates with a placed record — what encode materializes),
// out_ntpl_est (distinct qnames among hardclip/indel-kept records — the
// _kept_template_count the bucketed batcher and deep splitter use),
// out_rolerev (bit0/bit1 = majority reverse-orientation of role 0/1 slots),
// out_refid (last kept record's ref id), out_rx_rec (a record index whose RX
// is the family majority, -1 when none tagged). Returns 0.
int64_t bamio_encode_scan(
    int64_t n_fam, const int64_t* fam_start, const int32_t* fam_nrec,
    const uint16_t* flag, const int32_t* pos, const int32_t* ref_id,
    const int32_t* l_seq, const int64_t* var_off,
    const int32_t* left_clip, const int32_t* right_clip,
    const uint8_t* cigar_flags,
    const uint8_t* qname, int32_t qname_w,
    const uint8_t* rx, int32_t rx_w,
    int32_t indel_policy, int64_t indel_band,
    int64_t* out_lo, int64_t* out_window,
    int32_t* out_ntpl, int32_t* out_ntpl_est,
    uint8_t* out_rolerev, int32_t* out_refid, int64_t* out_rx_rec,
    int32_t* out_ti, uint8_t* out_role, uint8_t* out_keep) {
  (void)var_off;
  static thread_local EncScratch s;
  const bool drop_indels = indel_policy == 0;
  for (int64_t f = 0; f < n_fam; f++) {
    const int64_t start = fam_start[f];
    const int64_t nrec = fam_nrec[f];
    s.reset(size_t(nrec));
    int64_t lo = INT64_MAX, hi = INT64_MIN;
    int32_t refid = -1, ntpl = 0, est = 0;
    bool any = false;
    for (int64_t j = start; j < start + nrec; j++) {
      out_keep[j] = 0;
      out_ti[j] = -1;
      out_role[j] = 0;
      const uint8_t cf = cigar_flags[j];
      if (cf & 2) continue;  // hardclip: never encodes
      const bool has_indel = (cf & 1) != 0;
      if (has_indel && drop_indels) continue;
      // template entry (est counts it even when the read trims to nothing)
      const uint8_t* qn = qname + j * int64_t(qname_w);
      const size_t qlen = enc_keylen(qn, size_t(qname_w));
      size_t h = size_t(enc_hash(qn, qlen)) & s.mask;
      while (true) {
        if (s.tbl_gen[h] != s.gen) {
          s.tbl_gen[h] = s.gen;
          s.tbl_key[h] = j;
          s.tbl_ti[h] = -1;
          est++;
          break;
        }
        if (memcmp(qname + s.tbl_key[h] * int64_t(qname_w), qn, qlen) == 0)
          break;
        h = (h + 1) & s.mask;
      }
      const int64_t L =
          int64_t(l_seq[j]) - left_clip[j] - right_clip[j];
      if (L <= 0) continue;
      any = true;
      refid = ref_id[j];
      if (s.tbl_ti[h] < 0) {
        s.tbl_ti[h] = ntpl++;
        s.slot_rec.push_back(-1);
        s.slot_rec.push_back(-1);
        s.slot_state.push_back(0);
        s.slot_state.push_back(0);
      }
      const int32_t ti = s.tbl_ti[h];
      const int role = (flag[j] & 0x80) ? 1 : 0;  // FREAD2
      const size_t slot = size_t(ti) * 2 + size_t(role);
      if (s.slot_rec[slot] >= 0) out_keep[s.slot_rec[slot]] = 0;  // overwrite
      s.slot_rec[slot] = j;
      s.slot_state[slot] =
          uint8_t(1 | (((flag[j] >> 4) & 1) << 1));  // present | FREVERSE
      out_keep[j] = has_indel ? 2 : 1;
      out_ti[j] = ti;
      out_role[j] = uint8_t(role);
      // RX vote: absent/empty tag (NUL-led fixed-width field) not counted
      const uint8_t* rxp = rx + j * int64_t(rx_w);
      if (rxp[0] != 0) {
        const size_t rlen = enc_keylen(rxp, size_t(rx_w));
        size_t rh = size_t(enc_hash(rxp, rlen)) & s.mask;
        while (true) {
          if (s.rtbl_gen[rh] != s.gen) {
            s.rtbl_gen[rh] = s.gen;
            s.rtbl_key[rh] = j;
            s.rtbl_idx[rh] = int32_t(s.rx_count.size());
            s.rx_count.push_back(0);
            s.rx_first.push_back(j);
            break;
          }
          if (memcmp(rx + s.rtbl_key[rh] * int64_t(rx_w), rxp, rlen) == 0)
            break;
          rh = (rh + 1) & s.mask;
        }
        s.rx_count[size_t(s.rtbl_idx[rh])]++;
      }
      const int64_t p = pos[j];
      if (p < lo) lo = p;
      const int64_t e = p + L + (has_indel ? indel_band : 0);
      if (e > hi) hi = e;
    }
    out_lo[f] = any ? lo : -1;
    out_window[f] = any ? hi - lo : -1;
    out_ntpl[f] = ntpl;
    out_ntpl_est[f] = est;
    out_refid[f] = refid;
    // majority RX, ties to first inserted (Python max() over dict order)
    int64_t best = -1, best_n = 0;
    for (size_t k = 0; k < s.rx_count.size(); k++)
      if (s.rx_count[k] > best_n) {
        best_n = s.rx_count[k];
        best = s.rx_first[k];
      }
    out_rx_rec[f] = best;
    // per-role orientation vote over surviving (template, role) slots
    int votes[2][2] = {{0, 0}, {0, 0}};
    for (size_t k = 0; k < s.slot_state.size(); k++)
      if (s.slot_state[k] & 1) votes[k & 1][(s.slot_state[k] >> 1) & 1]++;
    out_rolerev[f] = uint8_t((votes[0][1] > votes[0][0] ? 1 : 0) |
                             (votes[1][1] > votes[1][0] ? 2 : 0));
  }
  return 0;
}

// Duplex-encode digest: the C twin of ops.encode.encode_duplex_families
// pass 1. Rows are keyed by exact flag value (the reference's 4-read group
// vocabulary, tools/2.extend_gap.py:117-131): 99->0, 163->1, 83->2, 147->3.
// Per record j: out_row[j] = 0..3 placed, -1 leftover (unknown flag,
// duplicate row, indel, or empty after trim), -2 hardclip-dropped (the
// reference silently drops these, never passes them through). Per family:
// out_start = max(lo-1, 0) (one margin column for the conversion prepend),
// out_window = hi-start (-1 when nothing places), out_rowmask (bit r =
// row r placed), out_gsize (non-hardclip record count; ==4 gates
// extend_eligible), out_refid, out_rx_rec (first placed record with a
// non-empty RX, -1 if none), out_nleft (leftover count — lets the Python
// side skip the per-family index scan for the common zero case).
int64_t bamio_duplex_scan(
    int64_t n_fam, const int64_t* fam_start, const int32_t* fam_nrec,
    const uint16_t* flag, const int32_t* pos, const int32_t* ref_id,
    const int32_t* l_seq,
    const int32_t* left_clip, const int32_t* right_clip,
    const uint8_t* cigar_flags,
    const uint8_t* rx, int32_t rx_w,
    int64_t* out_start, int64_t* out_window,
    uint8_t* out_rowmask, int32_t* out_gsize,
    int32_t* out_refid, int64_t* out_rx_rec, int32_t* out_nleft,
    int8_t* out_row) {
  for (int64_t f = 0; f < n_fam; f++) {
    const int64_t start = fam_start[f];
    const int64_t nrec = fam_nrec[f];
    int64_t lo = INT64_MAX, hi = INT64_MIN, rx_rec = -1;
    int32_t refid = -1, gsize = 0, nleft = 0;
    uint8_t mask = 0;
    bool any = false;
    for (int64_t j = start; j < start + nrec; j++) {
      const uint8_t cf = cigar_flags[j];
      if (cf & 2) {  // hardclip: dropped, not a leftover
        out_row[j] = -2;
        continue;
      }
      gsize++;
      int row;
      switch (flag[j]) {
        case 99: row = 0; break;
        case 163: row = 1; break;
        case 83: row = 2; break;
        case 147: row = 3; break;
        default: row = -1;
      }
      const int64_t L = int64_t(l_seq[j]) - left_clip[j] - right_clip[j];
      if (row < 0 || (mask & (1 << row)) || (cf & 1) || L <= 0) {
        out_row[j] = -1;  // leftover (first record wins a duplicate row)
        nleft++;
        continue;
      }
      mask |= uint8_t(1 << row);
      out_row[j] = int8_t(row);
      any = true;
      refid = ref_id[j];
      if (rx_rec < 0 && rx[j * int64_t(rx_w)] != 0) rx_rec = j;
      const int64_t p = pos[j];
      if (p < lo) lo = p;
      if (p + L > hi) hi = p + L;
    }
    const int64_t st = any ? (lo > 0 ? lo - 1 : 0) : -1;
    out_start[f] = st;
    out_window[f] = any ? hi - st : -1;
    out_rowmask[f] = mask;
    out_gsize[f] = gsize;
    out_refid[f] = refid;
    out_rx_rec[f] = rx_rec;
    out_nleft[f] = nleft;
  }
  return 0;
}

// Duplex pass 2: write placed reads (out_row >= 0) of families with
// rows[f] >= 0 into bases int8 / quals float32 / cover uint8(bool)
// [*, 4, w_pad]. Missing qualities (0xFF lead) stay zero. Returns records
// written, -1 on a window violation (scan/fill mismatch).
int64_t bamio_duplex_fill(
    int64_t n_fam, const int64_t* fam_start, const int32_t* fam_nrec,
    const int64_t* rows, const int64_t* starts,
    const int32_t* pos, const int32_t* l_seq, const int64_t* var_off,
    const int32_t* left_clip, const int32_t* right_clip,
    const uint8_t* seq, const uint8_t* qual,
    const int8_t* row_of, int64_t w_pad,
    int8_t* bases, float* quals, uint8_t* cover) {
  int64_t written = 0;
  for (int64_t f = 0; f < n_fam; f++) {
    const int64_t row = rows[f];
    if (row < 0) continue;
    const int64_t start = fam_start[f];
    for (int64_t j = start; j < start + fam_nrec[f]; j++) {
      if (row_of[j] < 0) continue;
      const int64_t L = int64_t(l_seq[j]) - left_clip[j] - right_clip[j];
      const int64_t off = int64_t(pos[j]) - starts[f];
      if (off < 0 || off + L > w_pad) return -1;
      const int64_t dst = (row * 4 + row_of[j]) * w_pad + off;
      const int64_t src = var_off[j] + left_clip[j];
      memcpy(bases + dst, seq + src, size_t(L));
      memset(cover + dst, 1, size_t(L));
      if (qual[var_off[j]] != 0xFF)
        for (int64_t i = 0; i < L; i++)
          quals[dst + i] = float(qual[src + i]);
      written++;
    }
  }
  return written;
}

// Pass 2: write direct-placed reads (keep==1) of families with rows[f] >= 0
// into bases/quals [*, t_pad, 2, w_pad] (bases pre-filled NBASE, quals
// zero). Missing qualities (0xFF lead byte, the BAM '*' fill) stay zero,
// matching ColumnarRecordView.codes_quals. Returns records written, or -1
// if any read falls outside its family window (scan/fill mismatch — a bug,
// not an input condition).
int64_t bamio_encode_fill(
    int64_t n_fam, const int64_t* fam_start, const int32_t* fam_nrec,
    const int64_t* rows, const int64_t* lo,
    const int32_t* pos, const int32_t* l_seq, const int64_t* var_off,
    const int32_t* left_clip, const int32_t* right_clip,
    const uint8_t* seq, const uint8_t* qual,
    const int32_t* ti, const uint8_t* role, const uint8_t* keep,
    int64_t t_pad, int64_t w_pad,
    int8_t* bases, uint8_t* quals) {
  int64_t written = 0;
  for (int64_t f = 0; f < n_fam; f++) {
    const int64_t row = rows[f];
    if (row < 0) continue;
    const int64_t start = fam_start[f];
    for (int64_t j = start; j < start + fam_nrec[f]; j++) {
      if (keep[j] != 1) continue;
      const int64_t L = int64_t(l_seq[j]) - left_clip[j] - right_clip[j];
      const int64_t off = int64_t(pos[j]) - lo[f];
      if (ti[j] < 0 || ti[j] >= t_pad || off < 0 || off + L > w_pad)
        return -1;
      const int64_t dst =
          ((row * t_pad + ti[j]) * 2 + role[j]) * w_pad + off;
      const int64_t src = var_off[j] + left_clip[j];
      memcpy(bases + dst, seq + src, size_t(L));
      if (qual[var_off[j]] != 0xFF) memcpy(quals + dst, qual + src, size_t(L));
      written++;
    }
  }
  return written;
}

}  // extern "C"
